"""MDS daemon: journaled filesystem metadata over RADOS (src/mds/).

The reference MDS keeps the namespace in a metadata pool — each
directory fragment is a RADOS object whose omap maps dentry name to the
encoded inode — and journals every mutation through osdc/Journaler
before acking (MDLog EUpdate events), writing dirty dirfrags back
lazily.  Crash recovery = load backing dirfrags + replay the journal
tail (up:replay -> up:active, MDCache::rejoin machinery reduced to the
single-MDS case).  File DATA never touches the MDS: clients stripe it
straight to the data pool (Striper) and report the new size back
(the reference tracks it via client caps; here an explicit setattr).

Wire surface: MClientRequest/MClientReply (messages/MClientRequest.h,
CEPH_MSG_CLIENT_REQUEST=24 / _REPLY=26) carrying json-ish op payloads.

Object naming in the metadata pool:
    dir.<ino:x>      dirfrag omap: name -> encoded dentry {ino, type}
    inode.<ino:x>    omap: encoded inode attrs (mode, size, times)
    mds.table        omap: next_ino
    mdlog.*          the Journaler stream + head
"""

from __future__ import annotations

import json
import threading
import time

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
from ceph_tpu.mds.caps import BUFFER, CapTable, caps_str
from ceph_tpu.mds.flock import (
    F_UNLCK, LockState, fcntl_range)
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osdc.journaler import Journaler

ROOT_INO = 1

S_IFDIR = 0o040000
S_IFREG = 0o100000


@register_message
class MClientRequest(Message):
    """fs client -> mds (CEPH_MSG_CLIENT_REQUEST=24)."""

    TYPE = 24

    def __init__(self, tid: int = 0, op: str = "", args: dict | None = None):
        super().__init__()
        self.tid = tid
        self.op = op
        self.args = args or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.str(self.op),
            e.bytes(json.dumps(self.args).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.op = d.str()
            self.args = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


@register_message
class MClientReply(Message):
    """mds -> fs client (CEPH_MSG_CLIENT_REPLY=26)."""

    TYPE = 26

    def __init__(self, tid: int = 0, result: int = 0,
                 out: dict | None = None):
        super().__init__()
        self.tid = tid
        self.result = result
        self.out = out or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.s32(self.result),
            e.bytes(json.dumps(self.out).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.result = d.s32()
            self.out = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


@register_message
class MClientSession(Message):
    """Session lifecycle, client <-> mds (CEPH_MSG_CLIENT_SESSION=22):
    request_open / open_ack / renew / request_close / close_ack."""

    TYPE = 22

    def __init__(self, tid: int = 0, op: str = "", client: int = 0):
        super().__init__()
        self.tid = tid
        self.op = op
        self.client = client

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.str(self.op), e.u64(self.client)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.op = d.str()
            self.client = d.u64()
        dec.versioned(1, body)


@register_message
class MClientCaps(Message):
    """Capability traffic (CEPH_MSG_CLIENT_CAPS=0x310).

    mds -> client: op 'revoke' (drop to `caps`, ack after flushing),
    'grant' (upgrade, no ack), 'invalidated' (inode unlinked).
    client -> mds: op 'ack' (revoke done — flushed size/mtime ride
    along), 'release' (last close)."""

    TYPE = 0x310

    def __init__(self, op: str = "", ino: int = 0, caps: int = 0,
                 seq: int = 0, client: int = 0, size: int = -1,
                 mtime: float = 0.0):
        super().__init__()
        self.op = op
        self.ino = ino
        self.caps = caps
        self.seq = seq
        self.client = client
        self.size = size
        self.mtime = mtime

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.str(self.op), e.u64(self.ino), e.u32(self.caps),
            e.u64(self.seq), e.u64(self.client), e.s64(self.size),
            e.f64(self.mtime)))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.op = d.str()
            self.ino = d.u64()
            self.caps = d.u32()
            self.seq = d.u64()
            self.client = d.u64()
            self.size = d.s64()
            self.mtime = d.f64()
        dec.versioned(1, body)


class _Park(Exception):
    """Request must wait for cap acks / lock release on this ino
    (the reference's MDSCacheObject add_waiter, as control flow)."""

    def __init__(self, ino: int):
        self.ino = ino


class Inode:
    __slots__ = ("ino", "mode", "size", "mtime")

    def __init__(self, ino: int, mode: int, size: int = 0,
                 mtime: float = 0.0):
        self.ino = ino
        self.mode = mode
        self.size = size
        self.mtime = mtime

    def is_dir(self) -> bool:
        return bool(self.mode & S_IFDIR)

    def to_dict(self) -> dict:
        return {"ino": self.ino, "mode": self.mode, "size": self.size,
                "mtime": self.mtime}

    @staticmethod
    def from_dict(d: dict) -> "Inode":
        return Inode(d["ino"], d["mode"], d.get("size", 0),
                     d.get("mtime", 0.0))


class MDSDaemon(Dispatcher):
    """Single-rank MDS (the reference scales ranks via dirfrag export;
    the namespace model below is rank-count agnostic)."""

    RECONNECT_GRACE = 2.0
    BEACON_INTERVAL = 1.0

    def __init__(self, mon_addr: str, metadata_pool: int | None = None,
                 data_pool: int | None = None,
                 ctx: CephTpuContext | None = None, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None,
                 gid: int | None = None):
        import os as _os
        self.gid = gid if gid is not None else \
            int.from_bytes(_os.urandom(6), "big")
        self.mon_addr = mon_addr
        self.rank: int | None = None
        self.ctx = ctx or CephTpuContext(f"mds.{self.gid}")
        self.name = EntityName("mds", 0)
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        #: 0 = no reconnect window; else: until this time, cap-granting
        #: client ops park while old clients reassert (MDS rejoin)
        self._reconnect_until = 0.0
        self._beacon_timer: threading.Timer | None = None
        self._lock = threading.RLock()
        #: ino -> Inode (inode cache; authoritative once loaded)
        self._inodes: dict[int, Inode] = {}
        #: ino -> {name: child_ino} (dirfrag cache)
        self._dirs: dict[int, dict[int, object]] = {}
        self._dirty_dirs: set[int] = set()
        self._dirty_inodes: set[int] = set()
        self._next_ino = ROOT_INO + 1
        self._journaled_since_flush = 0
        self.state = "boot"
        #: client sessions: client id -> {"con", "last_seen"}
        self._sessions: dict[int, dict] = {}
        #: capability table (Locker/Capability state)
        self.caps = CapTable()
        #: per-ino lock tables (flock.cc ceph_lock_state_t)
        self._locks: dict[int, LockState] = {}
        #: requests parked on an ino (waiting for cap acks / locks)
        self._parked: dict[int, list] = {}
        #: (ino, client) -> send time of the oldest un-acked revoke
        self._revoke_sent: dict[tuple[int, int], float] = {}
        #: grace before a silent revoke target / session is evicted
        self.revoke_grace = 4.0
        self.session_grace = 8.0
        #: parked requests older than this are answered with an error
        #: (EAGAIN for blocking locks) instead of lingering: the client
        #: RPC gives up before this, and granting a lock to a waiter
        #: that stopped waiting would orphan it forever
        self.park_ttl = 240.0
        self._tick_timer: threading.Timer | None = None

        self.objecter = RadosClient(mon_addr, ms_type=ms_type,
                                    auth_key=auth_key)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr
        self._stop = False
        self.journal: Journaler | None = None

    # -- lifecycle ------------------------------------------------------------

    def init(self) -> None:
        """Direct single-MDS bring-up (no FSMap registration): rank 0,
        journal 'mdlog'.  The FSMap path is init_standby()."""
        self.objecter.connect()
        self.rank = 0
        self.meta_io = self.objecter.open_ioctx(self.metadata_pool)
        self.journal = Journaler(self.meta_io, "mdlog")
        self._load_or_mkfs()
        self.state = "replay"
        n = self.journal.replay(
            lambda payload, _pos: self._replay_entry(payload))
        dout("mds", 5, "mds.0 replayed %d journal events", n)
        if n:
            self._flush_dirty()
            self.journal.trim()
        self.state = "active"
        self.msgr.bind(self._addr)
        self.msgr.start()
        self._schedule_tick()

    def init_standby(self) -> None:
        """FSMap bring-up: register with the mon via beacons and wait
        for a rank (MDSMonitor assignment); standbys idle until a
        failover promotes them."""
        self.objecter.connect()
        self.msgr.bind(self._addr)
        self.msgr.start()
        self.state = "standby"
        self._schedule_tick()
        self._beacon()

    def _beacon(self) -> None:
        if self._stop:
            return
        from ceph_tpu.mon.monitor import MMDSBeacon
        # fan out to EVERY mon (mon_addr is comma-separated): only the
        # leader assigns ranks, and any mon may be the leader
        for i, addr in enumerate(self.mon_addr.split(",")):
            try:
                con = self.msgr.connect_to(addr.strip(),
                                           EntityName("mon", i))
                con.send_message(MMDSBeacon(
                    gid=self.gid, addr=self.msgr.my_addr,
                    state=self.state,
                    rank=-1 if self.rank is None else self.rank))
            except OSError:
                continue
        self._beacon_timer = threading.Timer(self.BEACON_INTERVAL,
                                             self._beacon)
        self._beacon_timer.daemon = True
        self._beacon_timer.start()

    def _activate(self, rank: int) -> None:
        """Standby promoted to a rank: replay that rank's journal and
        open a reconnect window for the old clients' cap reasserts."""
        # the pool ids live in the FSMap; our objecter's first map
        # subscription may still be in flight — wait for it (outside
        # the lock: map delivery needs the objecter's dispatch)
        deadline = time.time() + 10.0
        while not self.objecter.osdmap.fs_db and time.time() < deadline:
            time.sleep(0.05)
        with self._lock:
            if self.rank is not None:
                return
            fs = self.objecter.osdmap.fs_db
            if not fs:
                dout("mds", 0, "mds gid %d: no fsmap in objecter map, "
                     "cannot activate", self.gid)
                return
            self.rank = rank
            if self.metadata_pool is None:
                self.metadata_pool = fs["metadata_pool"]
            if self.data_pool is None:
                self.data_pool = fs["data_pool"]
            self.meta_io = self.objecter.open_ioctx(self.metadata_pool)
            self.journal = Journaler(self.meta_io, f"mdlog.{rank}")
            self.state = "replay"
            self._load_or_mkfs()
            n = self.journal.replay(
                lambda payload, _pos: self._replay_entry(payload))
            dout("mds", 1, "mds gid %d rank %d: replayed %d events",
                 self.gid, rank, n)
            if n:
                self._flush_dirty()
                self.journal.trim()
            self._reconnect_until = time.time() + self.RECONNECT_GRACE
            self.state = "active"
            self._rerun(0)      # requests that arrived pre-activation

    def _schedule_tick(self) -> None:
        if self._stop:
            return
        self._tick_timer = threading.Timer(1.0, self._tick)
        self._tick_timer.daemon = True
        self._tick_timer.start()

    def _tick(self) -> None:
        try:
            now = time.time()
            with self._lock:
                if self._reconnect_until and now >= self._reconnect_until:
                    self._reconnect_until = 0.0
                    self._rerun(0)
                # silent revoke targets: the client never acked (dead or
                # wedged) — evict the WHOLE session, exactly like the
                # reference's session-kill on cap-revoke timeout.  A
                # half-evicted client that kept buffering while another
                # client was granted would corrupt the file underneath
                # the new holder.
                for (ino, client), t0 in list(self._revoke_sent.items()):
                    if now - t0 > self.revoke_grace:
                        dout("mds", 1, "mds cap revoke timeout: evicting "
                             "session of client.%d (ino %d)", client, ino)
                        s = self._sessions.get(client)
                        if s is not None:
                            # tell the client it is dead to us: it must
                            # drop caps/dirty state and remount
                            s["con"].send_message(MClientSession(
                                op="evicted", client=client))
                        self._evict_client(client)
                # stale sessions: no renew within the grace -> full evict
                for client, s in list(self._sessions.items()):
                    if now - s["last_seen"] > self.session_grace:
                        dout("mds", 1, "mds session timeout: evicting "
                             "client.%d", client)
                        self._evict_client(client)
                # expired parked requests: answer instead of lingering —
                # the client's RPC already gave up, and granting a lock
                # to an absent waiter would orphan it
                expired = []
                for ino, msgs in list(self._parked.items()):
                    keep = []
                    for m in msgs:
                        if now - m._parked_at > self.park_ttl:
                            expired.append(m)
                        else:
                            keep.append(m)
                    if keep:
                        self._parked[ino] = keep
                    else:
                        del self._parked[ino]
            for m in expired:
                err = -11 if m.op in ("setlk", "flock") else -110
                if m.op == "open":
                    # the opener gave up long ago (client RPC timeout <
                    # park_ttl): un-register its wanted bits or the ino
                    # would be stuck in sync mode forever.  ONLY when
                    # the client holds no issued caps — releasing a
                    # grant backing a live handle from an earlier open
                    # would hand exclusivity to someone else while this
                    # client still buffers under it.
                    with self._lock:
                        _p, ino, _n = self._resolve(m.args["path"])
                        cl = int(m.args.get("client", -1))
                        if ino is not None \
                                and self.caps.issued(ino, cl) == 0:
                            self._do_release(ino, cl)
                            self._rerun(ino)
                m.connection.send_message(
                    MClientReply(tid=m.tid, result=err, out={}))
        finally:
            self._schedule_tick()

    def _evict_client(self, client: int) -> None:
        """Drop every trace of a client: session, caps, locks —
        then re-run anything that was waiting on it."""
        self._sessions.pop(client, None)
        touched = set(self.caps.drop_client(client))
        for (ino, c) in list(self._revoke_sent):
            if c == client:
                del self._revoke_sent[(ino, c)]
        for ino, ls in list(self._locks.items()):
            if ls.drop_client(client):
                touched.add(ino)
            if ls.empty():
                del self._locks[ino]
        for ino in touched:
            self._upgrade_after_release(ino)
            self._rerun(ino)

    def shutdown(self) -> None:
        self._stop = True
        if self._tick_timer:
            self._tick_timer.cancel()
        if self._beacon_timer:
            self._beacon_timer.cancel()
        with self._lock:
            if self.journal is not None:
                self._flush_dirty()
                self.journal.trim()
        self.msgr.shutdown()
        self.objecter.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def _load_or_mkfs(self) -> None:
        fresh_fs = False
        try:
            table = self.meta_io.get_omap("mds.table")
            self._next_ino = int(table.get("next_ino", b"2").decode())
        except OSError:
            fresh_fs = True
        # the journal is PER RANK: its absence does not mean the fs is
        # fresh (a second active rank starts with an empty journal over
        # an existing namespace)
        try:
            self.journal.open()
        except OSError:
            self.journal.create()
        if fresh_fs:
            # fresh filesystem: root inode
            self._inodes[ROOT_INO] = Inode(ROOT_INO, S_IFDIR | 0o755)
            self._dirs[ROOT_INO] = {}
            self._dirty_dirs.add(ROOT_INO)
            self._dirty_inodes.add(ROOT_INO)
            self._flush_dirty()

    # -- backing store (dirfrag omap objects) ---------------------------------

    def _dir_obj(self, ino: int) -> str:
        return f"dir.{ino:x}"

    def _inode_obj(self, ino: int) -> str:
        return f"inode.{ino:x}"

    def _load_dir(self, ino: int) -> dict:
        d = self._dirs.get(ino)
        if d is not None:
            return d
        try:
            omap = self.meta_io.get_omap(self._dir_obj(ino))
            d = {name: int(v.decode()) for name, v in omap.items()}
        except OSError:
            d = {}
        self._dirs[ino] = d
        return d

    def _load_inode(self, ino: int) -> Inode | None:
        inode = self._inodes.get(ino)
        if inode is not None:
            return inode
        try:
            omap = self.meta_io.get_omap(self._inode_obj(ino))
        except OSError:
            return None
        if "json" not in omap:
            return None
        inode = Inode.from_dict(json.loads(omap["json"].decode()))
        self._inodes[ino] = inode
        return inode

    def _flush_dirty(self) -> None:
        """Write dirty dirfrags/inodes back (MDCache::flush, the lazy
        CDir commit), then persist the ino allocator."""
        for ino in sorted(self._dirty_dirs):
            d = self._dirs.get(ino, {})
            # rewrite wholesale: dirfrags are small omaps here
            try:
                self.meta_io.remove(self._dir_obj(ino))
            except OSError:
                pass
            self.meta_io.set_omap(
                self._dir_obj(ino),
                {name: str(child).encode() for name, child in d.items()})
        self._dirty_dirs.clear()
        for ino in sorted(self._dirty_inodes):
            inode = self._inodes.get(ino)
            if inode is None:
                continue
            self.meta_io.set_omap(
                self._inode_obj(ino),
                {"json": json.dumps(inode.to_dict()).encode()})
        self._dirty_inodes.clear()
        self.meta_io.set_omap(
            "mds.table", {"next_ino": str(self._next_ino).encode()})

    # -- journal (MDLog EUpdate) ----------------------------------------------

    def _journal(self, event: dict) -> None:
        self.journal.append_entry(json.dumps(event).encode())
        self.journal.flush()

    def _maybe_trim(self) -> None:
        """Segment boundary (MDLog trim): write dirty state back, then
        expire the journal.  MUST run only after the current event is
        both journaled AND applied — trimming first would expire an
        acked mutation that is in neither the journal nor the store."""
        self._journaled_since_flush += 1
        if self._journaled_since_flush >= 64:
            self._flush_dirty()
            self.journal.trim()
            self._journaled_since_flush = 0

    def _replay_entry(self, payload: bytes) -> None:
        ev = json.loads(payload.decode())
        self._apply(ev, replay=True)

    # -- namespace mutations (journaled, replayable) --------------------------

    def _apply(self, ev: dict, replay: bool = False) -> None:
        """Apply one journaled event to the cache.  Must be idempotent:
        replay re-applies events the backing store may already hold."""
        kind = ev["e"]
        if kind == "batch":
            # one journal entry, several sub-events: the atomic EUpdate
            # shape (rename's link+unlink must never tear)
            for sub in ev["events"]:
                self._apply(sub, replay=replay)
            return
        if kind == "alloc":
            self._next_ino = max(self._next_ino, ev["next_ino"])
            return
        if kind == "link":
            parent, name, ino = ev["parent"], ev["name"], ev["ino"]
            self._load_dir(parent)[name] = ino
            self._dirty_dirs.add(parent)
            if "mode" in ev:
                self._inodes[ino] = Inode(ino, ev["mode"], ev.get("size", 0),
                                          ev.get("mtime", 0.0))
                if self._inodes[ino].is_dir():
                    self._dirs.setdefault(ino, {})
                    self._dirty_dirs.add(ino)
                self._dirty_inodes.add(ino)
            return
        if kind == "unlink":
            parent, name = ev["parent"], ev["name"]
            d = self._load_dir(parent)
            ino = d.pop(name, None)
            self._dirty_dirs.add(parent)
            if ino is not None and ev.get("drop_inode"):
                self._inodes.pop(ino, None)
                self._dirs.pop(ino, None)
                try:
                    self.meta_io.remove(self._inode_obj(ino))
                except OSError:
                    pass
                try:
                    self.meta_io.remove(self._dir_obj(ino))
                except OSError:
                    pass
            return
        if kind == "setattr":
            inode = self._load_inode(ev["ino"])
            if inode is not None:
                if "size" in ev:
                    # size WRITEBACK is grow-only (a writer reporting
                    # how far it has written must never undo another
                    # client's longer write); only an explicit truncate
                    # carries plain size
                    if ev.get("grow"):
                        inode.size = max(inode.size, ev["size"])
                    else:
                        inode.size = ev["size"]
                if "mtime" in ev:
                    inode.mtime = ev["mtime"]
                if "mode" in ev:
                    inode.mode = ev["mode"]
                self._dirty_inodes.add(inode.ino)
            return
        raise ValueError(f"unknown journal event {kind!r}")

    def _mutate(self, ev: dict) -> None:
        """Journal-then-apply (the EUpdate ordering: an acked mutation
        is always recoverable), then maybe roll the segment."""
        self._journal(ev)
        self._apply(ev)
        self._maybe_trim()

    # -- path resolution ------------------------------------------------------

    def _resolve(self, path: str) -> tuple[int | None, int | None, str]:
        """path -> (parent_ino, ino, last_name); ino None if the leaf
        does not exist, parent None if an intermediate is missing."""
        parts = [p for p in path.split("/") if p]
        cur = ROOT_INO
        if not parts:
            return None, ROOT_INO, "/"
        for p in parts[:-1]:
            child = self._load_dir(cur).get(p)
            if child is None:
                return None, None, parts[-1]
            inode = self._load_inode(child)
            if inode is None or not inode.is_dir():
                return None, None, parts[-1]
            cur = child
        name = parts[-1]
        return cur, self._load_dir(cur).get(name), name

    # -- request handling -----------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if self._stop:
            return True
        if isinstance(msg, MClientRequest):
            self._handle_request(msg)
            return True
        if isinstance(msg, MClientSession):
            self._handle_session(msg)
            return True
        if isinstance(msg, MClientCaps):
            self._handle_caps_msg(msg)
            return True
        from ceph_tpu.mon.monitor import MMDSBeacon
        if isinstance(msg, MMDSBeacon):       # mon ack
            if msg.state == "ack" and msg.rank >= 0 \
                    and self.rank is None:
                self._activate(msg.rank)
            return True
        return False

    def _handle_request(self, msg) -> None:
        try:
            with self._lock:
                if "client" in msg.args:
                    s = self._sessions.get(int(msg.args["client"]))
                    if s is not None:
                        s["last_seen"] = time.time()
                        s["con"] = msg.connection
                result, out = self._handle(msg.op, msg.args)
                # reply INSIDE the lock: a grant reply must hit the wire
                # before any revoke a competing request issues against
                # it (per-connection FIFO then guarantees the client
                # installs the grant before seeing the revoke)
                msg.connection.send_message(
                    MClientReply(tid=msg.tid, result=result, out=out))
            return
        except _Park as p:
            # request waits for cap acks / lock release on this ino;
            # re-dispatched verbatim when the state changes
            if not hasattr(msg, "_parked_at"):
                msg._parked_at = time.time()
            with self._lock:
                self._parked.setdefault(p.ino, []).append(msg)
            return
        except Exception:
            from ceph_tpu.common.logging import get_logger
            get_logger("mds").exception("mds request %s failed", msg.op)
            result, out = -5, {}
        msg.connection.send_message(
            MClientReply(tid=msg.tid, result=result, out=out))

    def _rerun(self, ino: int) -> None:
        """Re-dispatch every request parked on an ino (waiters fire on
        any cap/lock state change there)."""
        msgs = self._parked.pop(ino, [])
        for m in msgs:
            self._handle_request(m)

    # -- sessions --------------------------------------------------------------

    def _handle_session(self, msg: MClientSession) -> None:
        with self._lock:
            if msg.op == "request_open":
                self._sessions[msg.client] = {
                    "con": msg.connection, "last_seen": time.time()}
                msg.connection.send_message(MClientSession(
                    tid=msg.tid, op="open_ack", client=msg.client))
            elif msg.op == "renew":
                s = self._sessions.get(msg.client)
                if s is not None:
                    s["last_seen"] = time.time()
                    s["con"] = msg.connection
            elif msg.op == "request_close":
                self._evict_client(msg.client)
                msg.connection.send_message(MClientSession(
                    tid=msg.tid, op="close_ack", client=msg.client))

    # -- capability traffic ----------------------------------------------------

    def _send_caps(self, client: int, m: MClientCaps) -> bool:
        s = self._sessions.get(client)
        if s is None:
            # no session to talk to: the grant is unrecallable — drop it
            self.caps.force_drop(m.ino, client)
            return False
        s["con"].send_message(m)
        return True

    def _issue_revokes(self, ino: int, revokes) -> None:
        now = time.time()
        for client, new_caps, seq in revokes:
            dout("mds", 10, "mds revoking ino %d client.%d -> %s",
                 ino, client, caps_str(new_caps))
            if self._send_caps(client, MClientCaps(
                    op="revoke", ino=ino, caps=new_caps, seq=seq,
                    client=client)):
                self._revoke_sent.setdefault((ino, client), now)

    def _handle_caps_msg(self, msg: MClientCaps) -> None:
        with self._lock:
            if msg.op == "ack":
                if self.caps.ack(msg.ino, msg.client, msg.seq):
                    self._revoke_sent.pop((msg.ino, msg.client), None)
                if msg.size >= 0:
                    # flushed dirty metadata rides the ack (journaled
                    # like any setattr so replay keeps it; grow-only —
                    # writeback never truncates)
                    if self._load_inode(msg.ino) is not None:
                        self._mutate({"e": "setattr", "ino": msg.ino,
                                      "size": msg.size, "grow": True,
                                      "mtime": msg.mtime})
            elif msg.op == "release":
                self._do_release(msg.ino, msg.client)
            else:
                return
            # rerun INSIDE the lock: outside it, the tick thread's
            # parked-list rewrite could re-insert a request this rerun
            # already dispatched (double lock grant)
            self._rerun(msg.ino)

    def _do_release(self, ino: int, client: int) -> None:
        for c, new_caps, seq in self.caps.release(ino, client):
            self._send_caps(c, MClientCaps(
                op="grant", ino=ino, caps=new_caps, seq=seq, client=c))
        self._revoke_sent.pop((ino, client), None)

    def _upgrade_after_release(self, ino: int) -> None:
        """Re-evaluate an ino after a holder vanished (release path is
        _do_release; this one serves evictions)."""
        for c, new_caps, seq in self.caps.release(ino, -1):
            self._send_caps(c, MClientCaps(
                op="grant", ino=ino, caps=new_caps, seq=seq, client=c))

    def _fresh_inode(self, ino: int, requester: int | None) -> None:
        """Before answering attrs: recall BUFFER from every OTHER
        holder so the size answered is the truth (Locker file_eval
        before a stat — the stat-sees-latest-write coherence rule)."""
        revokes = self.caps.recall(ino, BUFFER, exclude=requester)
        if revokes:
            self._issue_revokes(ino, revokes)
        if self.caps.pending_revokes(ino, exclude=requester):
            raise _Park(ino)

    def _handle(self, op: str, a: dict) -> tuple[int, dict]:
        client = int(a.get("client", -1))
        if self.state != "active":
            # the FSMap can point clients here before activation
            # completes (or while we are a standby a stale client
            # still targets): hold the request, activation reruns it
            raise _Park(0)
        if self._reconnect_until and op not in ("cap_reassert", "statfs"):
            if time.time() < self._reconnect_until:
                # reconnect window after a takeover: hold client ops
                # until the old clients reasserted their caps (ino 0 is
                # the window's wait key; the tick releases it)
                raise _Park(0)
            self._reconnect_until = 0.0
            self._rerun(0)

        if op == "cap_reassert":
            # failover rejoin: a surviving client re-asserts the caps
            # (and buffered size) it held under the dead rank — trusted
            # within the window, like the reference's reconnect phase
            for ent in a.get("caps", []):
                self.caps.reassert(int(ent["ino"]), client,
                                   int(ent["caps"]))
                if ent.get("size", -1) >= 0 and \
                        self._load_inode(int(ent["ino"])) is not None:
                    self._mutate({"e": "setattr", "ino": int(ent["ino"]),
                                  "size": int(ent["size"]), "grow": True,
                                  "mtime": float(ent.get("mtime", 0.0))})
            return 0, {}

        if op == "lookup":
            parent, ino, _name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None:
                return -2, {}
            if not inode.is_dir():
                # stat must see the latest write: flush buffered
                # writers first (parks until their acks land)
                self._fresh_inode(ino, requester=client)
                inode = self._load_inode(ino)
            return 0, {"inode": inode.to_dict()}

        if op == "getattr":
            inode = self._load_inode(a["ino"])
            if inode is None:
                return -2, {}
            if not inode.is_dir():
                self._fresh_inode(inode.ino, requester=client)
                inode = self._load_inode(inode.ino)
            return 0, {"inode": inode.to_dict()}

        if op == "open":
            # create-if-needed + capability issue (the Locker half of
            # Server::handle_client_open)
            parent, ino, name = self._resolve(a["path"])
            created = False
            if ino is None:
                if parent is None:
                    return -2, {}
                if not a.get("create"):
                    return -2, {}
                ino = self._alloc_ino()
                self._mutate({"e": "link", "parent": parent, "name": name,
                              "ino": ino,
                              "mode": S_IFREG | a.get("mode", 0o644),
                              "size": 0, "mtime": time.time()})
                created = True
            inode = self._load_inode(ino)
            if inode is None:
                return -2, {}
            if inode.is_dir():
                return -21, {}  # EISDIR
            granted, revokes = self.caps.open_want(
                ino, client, int(a["wanted"]))
            if revokes:
                self._issue_revokes(ino, revokes)
            if granted is None:
                raise _Park(ino)
            return 0, {"inode": inode.to_dict(), "caps": granted,
                       "cap_seq": self.caps.grant_seq(ino, client),
                       "created": created, "data_pool": self.data_pool}

        if op == "cap_release":
            # synchronous form of MClientCaps 'release' (close path
            # wants the upgrade side effects ordered before its return)
            self._do_release(a["ino"], client)
            self._rerun(a["ino"])
            return 0, {}

        if op == "open_cancel":
            # the client's open RPC timed out: withdraw whatever grant/
            # wanted registration the (possibly still-parked) open left,
            # so the ino does not stay in sync mode for a ghost
            parent, ino, _name = self._resolve(a["path"])
            if ino is not None:
                self._do_release(ino, client)
                self._rerun(ino)
            return 0, {}

        if op in ("setlk", "flock"):
            ino = a["ino"]
            if self._load_inode(ino) is None:
                return -2, {}
            ls = self._locks.setdefault(ino, LockState())
            owner = str(a["owner"])
            ltype = int(a["type"])
            if op == "setlk":
                start, end = fcntl_range(int(a.get("start", 0)),
                                         int(a.get("len", 0)))
                ok = ls.posix_set(client, owner, ltype, start, end)
            else:
                ok = ls.flock_set(client, owner, ltype)
            if ok:
                if ltype == F_UNLCK and ls.empty():
                    del self._locks[ino]
                # ANY successful change can unblock a waiter (unlock,
                # but also a WRLCK->RDLCK downgrade or a range shrink)
                self._rerun(ino)
                return 0, {}
            if a.get("wait"):
                raise _Park(ino)        # F_SETLKW / LOCK_EX blocking
            return -11, {}              # EAGAIN

        if op == "getlk":
            ls = self._locks.get(a["ino"])
            if ls is None:
                return 0, {"lock": None}
            start, end = fcntl_range(int(a.get("start", 0)),
                                     int(a.get("len", 0)))
            return 0, {"lock": ls.getlk(client, str(a["owner"]),
                                        int(a["type"]), start, end)}

        if op == "mkdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                return -17, {}  # EEXIST
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFDIR | a.get("mode", 0o755),
                          "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict()}

        if op == "create":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                inode = self._load_inode(ino)
                if inode is None or inode.is_dir():
                    return -21, {}  # EISDIR
                return 0, {"inode": inode.to_dict(),
                           "data_pool": self.data_pool}
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFREG | a.get("mode", 0o644),
                          "size": 0, "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict(),
                       "data_pool": self.data_pool}

        if op == "readdir":
            _parent, ino, _name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}  # ENOTDIR
            out = {}
            for name, child in sorted(self._load_dir(ino).items()):
                ci = self._load_inode(child)
                if ci is not None:
                    out[name] = ci.to_dict()
            return 0, {"entries": out}

        if op == "unlink":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is not None and inode.is_dir():
                return -21, {}
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            self._drop_ino_state(ino)
            return 0, {"ino": ino}

        if op == "rmdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}
            if self._load_dir(ino):
                return -39, {}  # ENOTEMPTY
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            return 0, {}

        if op == "rename":
            sp, sino, sname = self._resolve(a["src"])
            if sp is None or sino is None:
                return -2, {}
            dp, dino, dname = self._resolve(a["dst"])
            if dp is None:
                return -2, {}
            if dino is not None:
                return -17, {}
            # one atomic journal entry for link-at-dst + unlink-src (the
            # reference's single EUpdate): a crash can never leave the
            # inode reachable from both paths
            self._mutate({"e": "batch", "events": [
                {"e": "link", "parent": dp, "name": dname, "ino": sino},
                {"e": "unlink", "parent": sp, "name": sname}]})
            return 0, {"ino": sino}

        if op == "setattr":
            ev = {"e": "setattr", "ino": a["ino"]}
            for k in ("size", "mtime", "mode", "grow"):
                if k in a:
                    ev[k] = a[k]
            if self._load_inode(a["ino"]) is None:
                return -2, {}
            if "size" in a:
                # a size change (truncate / size writeback) must not
                # race a buffered writer: flush them first
                self._fresh_inode(a["ino"], requester=client)
            self._mutate(ev)
            return 0, {"inode": self._inodes[a["ino"]].to_dict()}

        if op == "statfs":
            return 0, {"next_ino": self._next_ino,
                       "data_pool": self.data_pool,
                       "metadata_pool": self.metadata_pool}

        return -22, {}

    def _drop_ino_state(self, ino: int) -> None:
        """Unlinked inode: its caps and locks evaporate; surviving
        holders are TOLD (op 'invalidated') so they stop buffering
        against purged data; anything parked re-runs (and sees
        ENOENT)."""
        for c in list(self.caps.holders(ino)):
            self._send_caps(c, MClientCaps(
                op="invalidated", ino=ino, caps=0, client=c))
            self.caps.force_drop(ino, c)
            self._revoke_sent.pop((ino, c), None)
        self._locks.pop(ino, None)
        self._rerun(ino)

    def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        # journal the allocation so replay never re-issues a used ino
        self._journal({"e": "alloc", "next_ino": self._next_ino})
        self._maybe_trim()
        return ino
