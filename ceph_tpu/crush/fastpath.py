"""Fused fast path for the canonical CRUSH rules on two-level maps.

The generic batched mapper (mapper_jax) re-draws the whole batch every retry
ladder iteration and pads every bucket row to the global max bucket size.  For
the rule shapes that carry ~all real placement traffic —

    take root
    chooseleaf firstn N type-t     (replicated pools; mapper.c:460-648)
    emit
and
    take root
    choose firstn N osd            (flat maps)
    emit

over a *uniform two-level* straw2 hierarchy (root -> type-t buckets ->
devices), a better device schedule exists because the retry ladder's r values
are shared across replicas: replica ``rep`` draws with r = rep + ftotal, so
the whole ladder for all reps only ever consumes root/leaf winners at
r in [0, numrep + max_ftotal).  The fast path therefore:

  1. precomputes straw2 winners for a block of r values — a fori_loop
     producing one r column per step (root (N, H) draw -> winner; that
     host's item/weight rows, padded only to the max *leaf* size, -> (N, S)
     leaf draw -> device + its is_out verdict);
  2. consumes them with numrep cheap masked while_loops whose bodies are
     (N,)-sized gathers and compares — no redraws, and reps 1..n-1 reuse the
     winners rep 0 already paid for;
  3. if any lane's ftotal walks past the precomputed block (rare: needs many
     consecutive collisions/rejections), a lax.cond re-runs the same
     computation with the full r range R = tries + numrep, which by
     construction cannot overflow — bit-exactness is unconditional, the big
     recompute just never happens on healthy maps.

(A weight-class decomposition — draws are monotone in the 16-bit hash, so
only the max-u item per distinct weight can win — was evaluated and rejected:
truncated-quotient ties between items are common at realistic bucket weights
(quotient spacing ~ crush_ln slope / w approaches 1 for host-sized w), so an
exactness fallback triggers on virtually every bulk call.  The argmax over
full per-item draws handles ties for free.)

Bit-exactness: validated against the scalar oracle (crush.mapper_ref) in
tests/test_mapper_jax.py::test_fastpath_* across skewed weights, reweights,
out OSDs, uneven host sizes, and forced-fallback configurations.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.ops.crush_kernel import is_out
from ceph_tpu.ops.straw2_u32 import (
    _ln_f32_error_bound, magic_tables, straw2_choose_index_approx)

from .types import (
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_EMIT,
    RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_TAKE,
    CrushMap,
)

NONE = jnp.int32(CRUSH_ITEM_NONE)

#: extra r-values beyond numrep precomputed in the first block.  6 covers
#: every lane on healthy maps (ftotal beyond 6 needs seven consecutive
#: collision/reject draws); the overflow cond recomputes with the full
#: range when it ever does not, so this is a latency knob, not a
#: correctness one.
DEFAULT_BLOCK = 6


@dataclass
class FastRule:
    """Host-side description of a fast-path-eligible rule."""

    kind: str                 # "chooseleaf" | "choose_flat"
    numrep_arg: int           # step arg1 (0 -> result_max)
    tries: int                # choose_total_tries + 1 (or SET override)
    vary_r: int
    root_ids: np.ndarray      # (H,) root bucket items
    root_w: np.ndarray        # (H,) int64 16.16 weights
    leaf_ids: np.ndarray | None   # (H, S) device ids, row per root item
    leaf_w: np.ndarray | None     # (H, S) int64, 0-padded
    max_devices: int


def detect(m: CrushMap, ruleno: int) -> FastRule | None:
    """Return a FastRule if ``ruleno`` on map ``m`` fits the fused kernel."""
    t = m.tunables
    if (t.choose_local_tries or t.choose_local_fallback_tries
            or t.chooseleaf_stable != 1):
        return None
    rule = m.rules[ruleno]
    if rule is None:
        return None
    tries = t.choose_total_tries + 1
    core: list = []
    for step in rule.steps:
        if step.op == RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                tries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0 and step.arg1 != 1:
                return None  # leaf retry loop not fused
        else:
            core.append(step)
    if len(core) != 3:
        return None
    take, choose, emit = core
    if take.op != RULE_TAKE or emit.op != RULE_EMIT:
        return None
    root = m.bucket(take.arg1)
    if root is None or root.alg != CRUSH_BUCKET_STRAW2 or root.size == 0:
        return None
    if root.size > 1024:
        return None  # (N, R, H) blocks would dwarf the iterative cost
    root_ids = np.asarray(root.items, dtype=np.int32)
    root_w = np.asarray(root.item_weights, dtype=np.int64)

    if choose.op == RULE_CHOOSE_FIRSTN and choose.arg2 == 0:
        # flat: every root item is a device
        if any(i < 0 or i >= m.max_devices for i in root.items):
            return None
        return FastRule(
            kind="choose_flat", numrep_arg=choose.arg1, tries=tries,
            vary_r=t.chooseleaf_vary_r, root_ids=root_ids, root_w=root_w,
            leaf_ids=None, leaf_w=None, max_devices=m.max_devices)

    if choose.op != RULE_CHOOSELEAF_FIRSTN:
        return None
    if not t.chooseleaf_descend_once:
        # without descend_once the leaf recursion retries inside the host
        # (recurse_tries = choose_tries, mapper.c:1041-1046); the fused
        # kernel only models the single-attempt (descend_once) semantics
        return None
    want_type = choose.arg2
    hosts = []
    for item in root.items:
        h = m.bucket(item)
        if (h is None or h.alg != CRUSH_BUCKET_STRAW2
                or h.type != want_type or h.size == 0):
            return None
        if any(i < 0 or i >= m.max_devices for i in h.items):
            return None
        hosts.append(h)
    s_max = max(h.size for h in hosts)
    leaf_ids = np.zeros((len(hosts), s_max), dtype=np.int32)
    leaf_w = np.zeros((len(hosts), s_max), dtype=np.int64)
    for row, h in enumerate(hosts):
        leaf_ids[row, :h.size] = h.items
        leaf_w[row, :h.size] = h.item_weights
    return FastRule(
        kind="chooseleaf", numrep_arg=choose.arg1, tries=tries,
        vary_r=t.chooseleaf_vary_r, root_ids=root_ids, root_w=root_w,
        leaf_ids=leaf_ids, leaf_w=leaf_w, max_devices=m.max_devices)


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------

def _draw_argmax(x, ids, weights, r, magic, off):
    """Straw2 winner position for one r value across the batch.

    x (N,) uint32; ids (S,) shared or (N, S) per-lane rows; weights /
    magic / off broadcastable to ids; r scalar uint32.  Returns (N,)
    positions.  Runs the u32 magic-division kernel (ops.straw2_u32) —
    bit-exact against the s64 kernel by exhaustive validation — whose
    argmin takes the first minimum, exactly the strict-``>`` scan of
    bucket_straw2_choose (mapper.c:374-380): truncation ties resolve to
    the lowest index for free.
    """
    idb = ids[None, :] if ids.ndim == 1 else ids
    wb = jnp.broadcast_to(
        weights[None, :] if weights.ndim == 1 else weights, idb.shape)
    mb = jnp.broadcast_to(
        magic[None, :, :] if magic.ndim == 2 else magic, (*idb.shape, 5))
    ob = jnp.broadcast_to(
        off[None, :] if off.ndim == 1 else off, idb.shape)
    return straw2_choose_index_approx(x, idb, r, wb, mb, ob)


def _consume(host_win, leaf_win, leaf_bad, numrep, tries, R, n):
    """Walk the firstn ladder over precomputed winners.

    host_win (N, R) int32: first-level item chosen at r (host id, or the
    device itself for flat rules).  leaf_win (N, R) int32: device at r.
    leaf_bad (N, R) bool: device rejected (is_out).  Returns
    (out_host, out_leaf, overflow): (N, numrep) selections with NONE holes
    and a per-lane flag for ftotal walking past R.
    """
    out_h = jnp.full((n, numrep), NONE, dtype=jnp.int32)
    out_l = jnp.full((n, numrep), NONE, dtype=jnp.int32)
    overflow = jnp.zeros((n,), dtype=bool)

    for rep in range(numrep):
        def cond(s):
            return jnp.any(s[3])

        def body(s, rep=rep, out_h=out_h, out_l=out_l):
            sel_h, sel_l, ft, act, ovf = s
            r = rep + ft
            within = r < R
            ridx = jnp.minimum(r, R - 1)[:, None]
            hb = jnp.take_along_axis(host_win, ridx, 1)[:, 0]
            lf = jnp.take_along_axis(leaf_win, ridx, 1)[:, 0]
            bad_l = jnp.take_along_axis(leaf_bad, ridx, 1)[:, 0]
            coll_h = jnp.any(out_h == hb[:, None], axis=1)
            coll_l = jnp.any(out_l == lf[:, None], axis=1)
            bad = coll_h | coll_l | bad_l
            place = act & within & ~bad
            sel_h = jnp.where(place, hb, sel_h)
            sel_l = jnp.where(place, lf, sel_l)
            ft = jnp.where(act & within & bad, ft + 1, ft)
            ovf = ovf | (act & ~within)
            act = act & within & bad & (ft < tries)
            return sel_h, sel_l, ft, act, ovf

        sel0 = jnp.full((n,), NONE, dtype=jnp.int32)
        sel_h, sel_l, _, _, overflow = jax.lax.while_loop(
            cond, body,
            (sel0, sel0, jnp.zeros((n,), jnp.int32),
             jnp.ones((n,), bool), overflow))
        out_h = out_h.at[:, rep].set(sel_h)
        out_l = out_l.at[:, rep].set(sel_l)
    return out_h, out_l, overflow


def _compact_rows(rows):
    order = jnp.argsort(rows == NONE, axis=1)
    return jnp.take_along_axis(rows, order, axis=1)


class FastMapper:
    """Compiled fast path for one (map, rule)."""

    def __init__(self, fr: FastRule):
        self.fr = fr
        _ln_f32_error_bound()   # measure eagerly: must be concrete by
        self.root_ids = jnp.asarray(fr.root_ids)   # the time jit traces
        self.root_w = jnp.asarray(fr.root_w)
        rm, ro = magic_tables(fr.root_w)
        self.root_magic = jnp.asarray(rm)
        self.root_off = jnp.asarray(ro)
        if fr.leaf_ids is not None:
            self.leaf_ids = jnp.asarray(fr.leaf_ids)
            self.leaf_w = jnp.asarray(fr.leaf_w)
            lm, lo = magic_tables(fr.leaf_w)
            self.leaf_magic = jnp.asarray(lm)
            self.leaf_off = jnp.asarray(lo)
        # the fused Pallas column kernels (2.5x the XLA path on this
        # backend); TPU-only — the CPU mesh tests keep the XLA path
        self._pallas = None
        if jax.default_backend() == "tpu":
            try:
                from ceph_tpu.ops.pallas_straw2 import PallasColumns
            except ImportError:   # pragma: no cover
                PallasColumns = None
            if PallasColumns is not None:
                # construction failures must surface, not silently
                # degrade to the slower XLA path
                self._pallas = PallasColumns(fr)

    def _winners_pallas(self, xs, reweight, R: int):
        """host_win/leaf_win/leaf_bad via the fused kernels (which pad
        the batch to their block quantum internally); (N, R) views."""
        n = xs.shape[0]
        pos, ids, bad = self._pallas.root_columns(xs, reweight, R)
        if self.fr.kind == "choose_flat":
            hw = lw = ids.T[:n]
            lb = bad.T[:n] != 0
        else:
            lid, lbad = self._pallas.leaf_columns(xs, pos, reweight, R)
            hw = ids.T[:n]
            lw = lid.T[:n]
            lb = lbad.T[:n] != 0
        return hw, lw, lb

    def _winners_pallas_fast(self, xs, reweight, R: int):
        """Approx-filtered winners with the exact columns as the
        certified fallback: if any (x, r) column had more than K items
        inside the f32 error band, the whole batch re-runs exact —
        bit-exactness is unconditional, the filter is only a schedule."""
        n = xs.shape[0]
        pos, ids, bad, ovf = self._pallas.root_columns_fast(
            xs, reweight, R)
        if self.fr.kind == "choose_flat":
            fast = (ids.T[:n], ids.T[:n], bad.T[:n] != 0)
            need_exact = jnp.any(ovf != 0)
        else:
            lid, lbad, ovf2 = self._pallas.leaf_columns_fast(
                xs, pos, reweight, R)
            fast = (ids.T[:n], lid.T[:n], lbad.T[:n] != 0)
            need_exact = jnp.any(ovf != 0) | jnp.any(ovf2 != 0)
        return jax.lax.cond(
            need_exact,
            lambda _: self._winners_pallas(xs, reweight, R),
            lambda _: fast, None)

    def _winners(self, xs, reweight, R: int):
        """host_win/leaf_win/leaf_bad for r in [0, R): a fori_loop producing
        one r column per step (bounds the (N, H) ln-matmul intermediates to a
        single r; an unrolled R-wide block OOMs HBM at bulk batch sizes)."""
        fr = self.fr
        n = xs.shape[0]
        hw0 = jnp.full((n, R), NONE, dtype=jnp.int32)
        lw0 = jnp.full((n, R), NONE, dtype=jnp.int32)
        lb0 = jnp.zeros((n, R), dtype=bool)

        def body(i, bufs):
            hw, lw, lb = bufs
            r = i.astype(jnp.uint32)
            pos = _draw_argmax(xs, self.root_ids, self.root_w, r,
                               self.root_magic, self.root_off)
            first = self.root_ids[pos]                         # (N,)
            if fr.kind == "choose_flat":
                leaf = first
            else:
                # r_leaf = vary_r ? r >> (vary_r-1) : 0 (mapper.c:578)
                if fr.vary_r:
                    r_leaf = r >> jnp.uint32(fr.vary_r - 1)
                else:
                    r_leaf = jnp.uint32(0)
                ids = self.leaf_ids[pos]                       # (N, S)
                w = self.leaf_w[pos]                           # (N, S)
                lpos = _draw_argmax(xs, ids, w, r_leaf,
                                    self.leaf_magic[pos],
                                    self.leaf_off[pos])
                leaf = jnp.take_along_axis(ids, lpos[:, None], 1)[:, 0]
            bad = is_out(reweight, leaf, xs)
            hw = jax.lax.dynamic_update_slice(hw, first[:, None], (0, i))
            lw = jax.lax.dynamic_update_slice(lw, leaf[:, None], (0, i))
            lb = jax.lax.dynamic_update_slice(lb, bad[:, None], (0, i))
            return hw, lw, lb

        return jax.lax.fori_loop(0, R, body, (hw0, lw0, lb0))

    def run(self, xs, reweight, result_max: int,
            block: int = DEFAULT_BLOCK):
        """Full do_rule: returns (N, result_max) NONE-compacted placements."""
        fr = self.fr
        numrep = fr.numrep_arg
        if numrep <= 0:
            numrep += result_max
        n = xs.shape[0]
        if numrep <= 0:
            return jnp.full((n, result_max), NONE, dtype=jnp.int32)
        Rf = fr.tries + numrep
        R0 = min(numrep + block, Rf)

        def winners_for(R):
            if self._pallas is None:
                return self._winners
            # the candidate-packed approx kernels (winners_pallas_fast)
            # are bit-exact and interpret-verified, but the axon AOT
            # backend compiles their two-phase program pathologically
            # (minutes to never) at bulk shapes — opt-in only until the
            # toolchain digests them
            import os
            from ceph_tpu.ops.pallas_straw2 import _KPACK
            if (os.environ.get("CEPH_TPU_FAST_FILTER") == "1"
                    and R * _KPACK <= 128):
                return self._winners_pallas_fast
            return self._winners_pallas

        hw, lw, lb = winners_for(R0)(xs, reweight, R0)
        out_h, out_l, ovf = _consume(hw, lw, lb, numrep, fr.tries, R0, n)

        def slow(_):
            hw2, lw2, lb2 = winners_for(Rf)(xs, reweight, Rf)
            oh, ol, _ = _consume(hw2, lw2, lb2, numrep, fr.tries, Rf, n)
            return oh, ol

        out_h, out_l = jax.lax.cond(
            jnp.any(ovf), slow, lambda _: (out_h, out_l), None)
        res = out_l if fr.kind == "chooseleaf" else out_h
        res = _compact_rows(res)
        if numrep < result_max:
            res = jnp.concatenate(
                [res, jnp.full((n, result_max - numrep), NONE,
                               dtype=jnp.int32)], axis=1)
        return res[:, :result_max]
