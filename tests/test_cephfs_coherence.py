"""CephFS client capabilities, POSIX coherence, and file locking
(Locker.cc / flock.cc observable behaviour through two live clients).

The contract under test: whatever caching/buffering a client does under
its granted caps, a SECOND client's reads/stats always see the latest
acked write — because the MDS revokes conflicting caps (forcing a
flush) before answering.
"""

from __future__ import annotations

import threading
import time

import pytest

from ceph_tpu.cephfs import BUFFER, CACHE, CephFS, F_RDLCK, F_WRLCK, WR
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    yield c
    c.stop()


@pytest.fixture
def two_fs(cluster):
    a = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback",
               client_id=71)
    b = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback",
               client_id=72)
    a.mount()
    b.mount()
    yield a, b
    a.unmount()
    b.unmount()


# -- capability grants -------------------------------------------------------

def test_lone_writer_buffers_then_flushes_on_close(two_fs):
    a, _b = two_fs
    f = a.open("/lone", "w")
    assert f.state.caps & BUFFER      # lone opener: full caps
    f.write(b"buffered!")
    # our own stat sees our buffered size (client-side overlay; the
    # MDS recalls only OTHER clients' buffers)
    assert a.stat("/lone")["size"] == 9
    f.close()
    assert a.stat("/lone")["size"] == 9


def test_stat_from_other_client_recalls_buffer(two_fs):
    a, b = two_fs
    f = a.open("/statme", "w")
    f.write(b"x" * 1000)
    # A holds BUFFER: size is dirty client-side only.  B's stat must
    # force A's flush before answering (the coherence rule).
    st = b.stat("/statme")
    assert st["size"] == 1000
    f.close()


def test_second_reader_shares_cache(two_fs):
    a, b = two_fs
    with a.open("/shared", "w") as f:
        f.write(b"data")
    fa = a.open("/shared", "r")
    fb = b.open("/shared", "r")
    assert fa.state.caps & CACHE
    assert fb.state.caps & CACHE
    assert not fb.state.caps & WR
    fa.close()
    fb.close()


def test_mixed_writer_reader_goes_sync_and_coherent(two_fs):
    a, b = two_fs
    fw = a.open("/mixed", "w")
    fw.write(b"first-version")          # buffered (lone writer)
    fr = b.open("/mixed", "r")
    # B's open revoked A's buffer: A flushed, both are in sync mode now
    assert not fw.state.caps & BUFFER
    assert not fr.state.caps & CACHE
    assert fr.read() == b"first-version"
    # sync mode: every subsequent write is immediately visible
    fw.seek(0)
    fw.write(b"SECON")
    fr.seek(0)
    assert fr.read() == b"SECON-version"
    fw.close()
    fr.close()


def test_interleaved_writes_two_clients_coherent(two_fs):
    """Conflicting writers on one file: all I/O degrades to sync and
    each client's reads see the other's latest write."""
    a, b = two_fs
    fa = a.open("/both", "w")
    fb = b.open("/both", "w")
    assert not fa.state.caps & BUFFER and not fb.state.caps & BUFFER
    for i in range(5):
        fa.seek(i * 10)
        fa.write(f"A{i:04d}x".encode())
        fb.seek(i * 10 + 5)
        fb.write(f"B{i:04d}".encode())
        fa.seek(i * 10)
        got_a = fa.read(11)
        assert got_a[5:10] == f"B{i:04d}".encode(), (i, got_a)
    fb.seek(0)
    assert fb.read(5) == b"A0000"   # B sees A's writes too
    fa.close()
    fb.close()


def test_writer_upgraded_back_when_reader_leaves(two_fs):
    a, b = two_fs
    fw = a.open("/upgrade", "w")
    fr = b.open("/upgrade", "r")
    assert not fw.state.caps & BUFFER   # shared: sync
    fr.close()
    deadline = time.time() + 5
    while not fw.state.caps & BUFFER and time.time() < deadline:
        time.sleep(0.05)
    # Locker re-evals on release: the now-lone writer buffers again
    assert fw.state.caps & BUFFER
    fw.write(b"fast again")
    fw.close()


def test_dead_client_evicted_not_wedged(cluster):
    """A SIGKILL'd client (no unmount, no acks) must not block others:
    the MDS evicts it on session/revoke timeout."""
    dead = CephFS(cluster.mon_host, cluster.mds.addr,
                  ms_type="loopback", client_id=80)
    dead.mount()
    f = dead.open("/zombie", "w")
    f.write(b"never flushed")
    # simulate SIGKILL: drop the messengers without close/unmount
    dead._stop = True
    if dead._renew_timer:
        dead._renew_timer.cancel()
    dead.msgr.shutdown()
    dead.rados.shutdown()

    live = CephFS(cluster.mon_host, cluster.mds.addr,
                  ms_type="loopback", client_id=81)
    live.mount()
    try:
        t0 = time.time()
        st = live.stat("/zombie")       # parks until eviction fires
        assert time.time() - t0 < cluster.mds.revoke_grace + 8
        # the zombie's buffered data is lost (never flushed) — size is
        # whatever the MDS had acked: 0.  Crucially we got an answer.
        assert st["size"] == 0
        with live.open("/zombie", "w") as g:
            g.write(b"new owner")
        assert live.stat("/zombie")["size"] == 9
    finally:
        live.unmount()


# -- locks -------------------------------------------------------------------

def test_fcntl_ranges_across_clients(two_fs):
    a, b = two_fs
    with a.open("/lockf", "w") as f:
        f.write(b"z" * 100)
    fa = a.open("/lockf", "r")
    fb = b.open("/lockf", "r")
    fa.lockf(F_WRLCK, 0, 50)
    with pytest.raises(OSError):        # EAGAIN
        fb.lockf(F_WRLCK, 40, 20)
    fb.lockf(F_WRLCK, 50, 50)           # disjoint: fine
    got = fb.getlk(F_WRLCK, 0, 10)
    assert got is not None and got["type"] == F_WRLCK
    fa.lockf(F_UNLCK := 2, 0, 50)
    fb.lockf(F_WRLCK, 0, 50)            # now free
    fa.close()
    fb.close()


def test_blocking_lock_granted_on_unlock(two_fs):
    a, b = two_fs
    with a.open("/lockw", "w") as f:
        f.write(b"z" * 10)
    fa = a.open("/lockw", "r")
    fb = b.open("/lockw", "r")
    fa.lockf(F_WRLCK, 0, 10)
    got_it = threading.Event()

    def blocked():
        fb.lockf(F_WRLCK, 0, 10, wait=True)
        got_it.set()

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not got_it.is_set()          # genuinely blocked
    fa.lockf(2, 0, 10)                  # unlock
    assert got_it.wait(5), "blocked locker never woke"
    fa.close()
    fb.close()


def test_flock_whole_file_and_handle_close_release(two_fs):
    a, b = two_fs
    with a.open("/flk", "w") as f:
        f.write(b"z")
    fa = a.open("/flk", "r")
    fb = b.open("/flk", "r")
    fa.flock(F_WRLCK)
    with pytest.raises(OSError):
        fb.flock(F_RDLCK)
    fa.close()                          # handle close releases flock
    fb.flock(F_WRLCK)                   # now acquirable
    fb.close()


def test_lock_released_on_client_death(cluster):
    dead = CephFS(cluster.mon_host, cluster.mds.addr,
                  ms_type="loopback", client_id=90)
    dead.mount()
    with dead.open("/dlock", "w") as f:
        f.write(b"z")
    fd = dead.open("/dlock", "r")
    fd.lockf(F_WRLCK, 0, 1)
    dead._stop = True
    if dead._renew_timer:
        dead._renew_timer.cancel()
    dead.msgr.shutdown()
    dead.rados.shutdown()

    live = CephFS(cluster.mon_host, cluster.mds.addr,
                  ms_type="loopback", client_id=91)
    live.mount()
    try:
        fl = live.open("/dlock", "r")
        # blocks until the dead session is evicted, then grants
        fl.lockf(F_WRLCK, 0, 1, wait=True)
        fl.close()
    finally:
        live.unmount()


def test_stalled_client_session_killed_and_notified(cluster):
    """A live client that ignores revokes past the grace loses its whole
    session (reference: session kill + blocklist on revoke timeout): the
    MDS notifies it, its caps die, and the other client proceeds."""
    stall = CephFS(cluster.mon_host, cluster.mds.addr,
                   ms_type="loopback", client_id=95)
    stall.mount()
    f = stall.open("/stall", "w")
    f.write(b"never acked")
    # wedge the client: it silently drops every cap message
    stall._handle_caps = lambda msg: None

    live = CephFS(cluster.mon_host, cluster.mds.addr,
                  ms_type="loopback", client_id=96)
    live.mount()
    try:
        st = live.stat("/stall")       # parks until the eviction
        assert st["size"] == 0         # unflushed buffer died with it
        deadline = time.time() + 5
        while not stall._evicted and time.time() < deadline:
            time.sleep(0.05)
        assert stall._evicted          # the kill was notified
        with pytest.raises(OSError):
            stall.stat("/stall")       # evicted session refuses ops
    finally:
        live.unmount()
        stall._stop = True
        if stall._renew_timer:
            stall._renew_timer.cancel()
        stall.msgr.shutdown()
        stall.rados.shutdown()


def test_unlink_invalidates_other_holders(two_fs):
    """Unlinking a file another client has open+buffered notifies that
    holder: its caps are void, buffered data is dropped, and its close
    surfaces an error instead of silently recreating purged data."""
    a, b = two_fs
    f = a.open("/doomed", "w")
    f.write(b"soon gone")
    b.unlink("/doomed")
    deadline = time.time() + 5
    while f.state.caps and time.time() < deadline:
        time.sleep(0.05)
    assert f.state.caps == 0 and not f.state.dirty
    with pytest.raises(OSError):
        f.close()                      # size report hits ENOENT
