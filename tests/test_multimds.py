"""Multi-active MDS: subtree delegation, request forwarding, export
migration (caps recalled, locks handed over), cross-rank coherence, and
the load balancer (Migrator/MDBalancer reduced)."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.cephfs import CephFS, F_WRLCK
from ceph_tpu.mds.caps import BUFFER
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def two_rank_cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    rc, out = client.mon_command({"prefix": "fs new", "fs_name": "cephfs",
                                  "metadata": meta, "data": data})
    assert rc == 0, out
    rc, out = client.mon_command({"prefix": "fs set", "var": "max_mds",
                                  "val": 2})
    assert rc == 0, out
    c.run_fs_mds(2)
    deadline = time.time() + 15
    while time.time() < deadline:
        ranks = (client.osdmap.fs_db or {}).get("ranks", {})
        if len(ranks) == 2:
            break
        time.sleep(0.1)
    assert len(client.osdmap.fs_db["ranks"]) == 2
    yield c, client
    c.stop()


@pytest.fixture
def fs(two_rank_cluster):
    c, _client = two_rank_cluster
    f = CephFS(c.mon_host, ms_type="loopback", client_id=501)
    f.mount()
    yield f
    f.unmount()


def _rank_of(c, gid):
    for d in c.fs_mds:
        if d.gid == gid:
            return d
    raise AssertionError(f"gid {gid} not running")


def test_two_ranks_active(two_rank_cluster):
    c, client = two_rank_cluster
    ranks = client.osdmap.fs_db["ranks"]
    d0 = _rank_of(c, ranks["0"]["gid"])
    d1 = _rank_of(c, ranks["1"]["gid"])
    # poll on STATE: rank is assigned first, then activation replays
    # the journal (RADOS I/O) before state flips to active
    deadline = time.time() + 30
    while not (d0.state == d1.state == "active") \
            and time.time() < deadline:
        time.sleep(0.05)
    assert {d0.rank, d1.rank} == {0, 1}
    assert d0.state == d1.state == "active"


def test_export_and_forwarding(fs, two_rank_cluster):
    c, client = two_rank_cluster
    fs.mkdir("/proj")
    fs.mkdir("/proj/deep")
    with fs.open("/proj/deep/f", "w") as f:
        f.write(b"before export")
    out = fs.export_dir("/proj", 1)
    assert out.get("inos", 0) >= 2
    # namespace fully usable at the new authority (client forwards)
    assert fs.stat("/proj/deep/f")["size"] == 13
    with fs.open("/proj/deep/f", "r") as f:
        assert f.read() == b"before export"
    with fs.open("/proj/new", "w") as f:
        f.write(b"made on rank 1")
    assert sorted(fs.listdir("/proj")) == ["deep", "new"]
    # rank 1 is really serving it: the daemon's own counters moved
    ranks = client.osdmap.fs_db["ranks"]
    d1 = _rank_of(c, ranks["1"]["gid"])
    assert d1._req_counts.get("/proj", 0) > 0
    # rank 0 still owns the rest
    fs.mkdir("/other")
    assert "other" in fs.listdir("/")


def test_fresh_client_discovers_delegation(fs, two_rank_cluster):
    c, _client = two_rank_cluster
    fs.mkdir("/disc")
    with fs.open("/disc/x", "w") as f:
        f.write(b"findme")
    fs.export_dir("/disc", 1)
    g = CephFS(c.mon_host, ms_type="loopback", client_id=502)
    g.mount()
    try:
        # no hints: first request goes to rank 0 and is forwarded
        assert g.stat("/disc/x")["size"] == 6
        assert g._path_rank.get("/disc/x") == 1
    finally:
        g.unmount()


def test_coherence_across_ranks(fs, two_rank_cluster):
    """Cap coherence holds for a subtree served by rank 1: a buffered
    writer there is flushed when a second client stats the file."""
    c, _client = two_rank_cluster
    fs.mkdir("/r1")
    fs.export_dir("/r1", 1)
    f = fs.open("/r1/data", "w")
    assert f.state.rank == 1
    assert f.state.caps & BUFFER
    f.write(b"z" * 777)
    g = CephFS(c.mon_host, ms_type="loopback", client_id=503)
    g.mount()
    try:
        assert g.stat("/r1/data")["size"] == 777
    finally:
        g.unmount()
    f.close()


def test_export_migrates_locks(fs, two_rank_cluster):
    c, _client = two_rank_cluster
    fs.mkdir("/locked")
    with fs.open("/locked/f", "w") as f:
        f.write(b"z" * 10)
    fa = fs.open("/locked/f", "r")
    fa.lockf(F_WRLCK, 0, 10)
    fs.export_dir("/locked", 1)
    # the lock followed the subtree: another client still conflicts
    g = CephFS(c.mon_host, ms_type="loopback", client_id=504)
    g.mount()
    try:
        fb = g.open("/locked/f", "r")
        with pytest.raises(OSError):
            fb.lockf(F_WRLCK, 0, 10)
        fa.lockf(2, 0, 10)          # unlock (routed to rank 1)
        fb.lockf(F_WRLCK, 0, 10)    # now acquirable
        fb.close()
    finally:
        g.unmount()
    fa.close()


def test_cross_subtree_rename_is_exdev(fs, two_rank_cluster):
    fs.mkdir("/xsrc")
    fs.mkdir("/xdst")
    with fs.open("/xsrc/m", "w") as f:
        f.write(b"m")
    fs.export_dir("/xdst", 1)
    with pytest.raises(OSError) as ei:
        fs.rename("/xsrc/m", "/xdst/m")
    assert ei.value.errno == 18      # EXDEV
    # same-subtree rename still fine
    fs.rename("/xsrc/m", "/xsrc/m2")
    assert "m2" in fs.listdir("/xsrc")


def test_autobalance_exports_hot_subtree(fs, two_rank_cluster):
    c, client = two_rank_cluster
    ranks = client.osdmap.fs_db["ranks"]
    d0 = _rank_of(c, ranks["0"]["gid"])
    d1 = _rank_of(c, ranks["1"]["gid"])
    fs.mkdir("/hot")
    with fs.open("/hot/f", "w") as f:
        f.write(b"x")
    try:
        d0.bal_auto = True
        d0.bal_floor = 10.0
        d0.bal_factor = 2.0
        deadline = time.time() + 30
        moved = False
        while time.time() < deadline and not moved:
            for _ in range(50):
                fs.stat("/hot/f")    # hammer the subtree
            moved = d1._load_subtrees(force=True).get("/hot") == 1
        assert moved, "balancer never exported the hot subtree"
        # and it still serves correctly afterwards
        assert fs.stat("/hot/f")["size"] == 1
    finally:
        d0.bal_auto = False


def test_ino_authority_survives_exporter_restart(two_rank_cluster):
    """After rank 0 exports a subtree and then CRASHES, its replacement
    must still forward ino-based ops for exported inos (authority is
    derived from the durable subtree table + parent backpointers, not
    the dead daemon's memory)."""
    c, client = two_rank_cluster
    fs = CephFS(c.mon_host, ms_type="loopback", client_id=505)
    fs.mount()
    try:
        fs.mkdir("/durable")
        with fs.open("/durable/f", "w") as f:
            f.write(b"payload")
        ino = fs.stat("/durable/f")["ino"]
        fs.export_dir("/durable", 1)
        c.run_fs_mds(1)              # standby for the coming failover
        gid0 = client.osdmap.fs_db["ranks"]["0"]["gid"]
        c.crash_fs_mds(next(d for d in c.fs_mds if d.gid == gid0))
        deadline = time.time() + 25
        while time.time() < deadline:
            ent = client.osdmap.fs_db["ranks"].get("0")
            if ent and ent["gid"] != gid0:
                break
            time.sleep(0.1)
        # ino op aimed at the REPLACEMENT rank 0: it was not running at
        # export time, yet must forward to rank 1 (getattr answers with
        # the inode only at the true authority)
        out = fs._request("getattr", {"ino": ino}, rank=0)
        assert out["inode"]["size"] == 7
        assert fs._caps.get(ino) is None or True  # routing only
        # and path ops keep working end to end
        assert fs.stat("/durable/f")["size"] == 7
    finally:
        fs.unmount()
