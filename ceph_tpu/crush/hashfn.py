"""rjenkins1 32-bit hash family — the only hash CRUSH uses.

Semantics match src/crush/hash.c exactly: Robert Jenkins' 1997 96-bit mix applied to
fixed seeds (crush_hash_seed = 1315423911, x = 231232, y = 1232) in arity-specific
schedules (hash.c:26-90).  Scalar variants operate on Python ints (the oracle); the
batched jax variants live in ops.crush_kernel and are validated against these.
"""

from __future__ import annotations


CRUSH_HASH_RJENKINS1 = 0
CRUSH_HASH_SEED = 1315423911

_M32 = 0xFFFFFFFF


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    a = (a - b - c) & _M32; a ^= c >> 13
    b = (b - c - a) & _M32; b ^= (a << 8) & _M32
    c = (c - a - b) & _M32; c ^= b >> 13
    a = (a - b - c) & _M32; a ^= c >> 12
    b = (b - c - a) & _M32; b ^= (a << 16) & _M32
    c = (c - a - b) & _M32; c ^= b >> 5
    a = (a - b - c) & _M32; a ^= c >> 3
    b = (b - c - a) & _M32; b ^= (a << 10) & _M32
    c = (c - a - b) & _M32; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= _M32
    h = (CRUSH_HASH_SEED ^ a) & _M32
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= _M32; b &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= _M32; b &= _M32; c &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def crush_hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= _M32; b &= _M32; c &= _M32; d &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= _M32; b &= _M32; c &= _M32; d &= _M32; e &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h
