"""shec / lrc / clay plugins: exhaustive erasure sweeps (the pattern of
src/test/erasure-code/TestErasureCodeIsa.cc:399,525), locality
(minimum_to_decode cost) checks, and clay's sub-chunk repair bandwidth.
"""

from itertools import combinations

import numpy as np
import pytest

from ceph_tpu.ec import registry_instance


def roundtrip(codec, data: bytes, erase: set) -> bool:
    """encode, drop `erase`, decode everything back; False if the codec
    reported the pattern unrecoverable."""
    n = codec.get_chunk_count()
    enc = codec.encode(set(range(n)), data)
    chunks = {i: enc[i] for i in range(n) if i not in erase}
    try:
        dec = codec.decode(set(range(n)), chunks)
    except IOError:
        return False
    for i in range(n):
        assert dec[i] == enc[i], f"chunk {i} corrupted (erase={erase})"
    return True


DATA = bytes(np.random.default_rng(7).integers(0, 256, 2500, dtype=np.uint8))


class TestShec:
    @pytest.fixture(scope="class")
    def codec(self):
        return registry_instance().factory(
            "shec", {"k": "4", "m": "3", "c": "2", "runtime": "cpu"})

    def test_single_erasures_exhaustive(self, codec):
        n = codec.get_chunk_count()
        for i in range(n):
            assert roundtrip(codec, DATA, {i})

    def test_double_erasures_exhaustive(self, codec):
        """c=2: every 2-failure pattern must decode."""
        n = codec.get_chunk_count()
        for pair in combinations(range(n), 2):
            assert roundtrip(codec, DATA, set(pair)), pair

    def test_triple_erasures_report_cleanly(self, codec):
        """Beyond c the code is probabilistic: either the bytes round
        trip or the codec raises IOError — never silent corruption
        (roundtrip asserts equality whenever decode claims success)."""
        n = codec.get_chunk_count()
        ok = sum(roundtrip(codec, DATA, set(t))
                 for t in combinations(range(n), 3))
        assert ok > 0   # some triples are recoverable

    def test_local_repair_is_cheaper_than_k(self, codec):
        """The recovery-bandwidth trade: one lost data chunk reads a
        shingle (l chunks), not k."""
        n = codec.get_chunk_count()
        avail = set(range(n)) - {0}
        need = codec.minimum_to_decode({0}, avail)
        width = len(codec.window(0))
        assert len(need) <= width + 1
        # and the chosen set actually decodes
        enc = codec.encode(set(range(n)), DATA)
        dec = codec.decode({0}, {i: enc[i] for i in need})
        assert dec[0] == enc[0]

    def test_min_to_decode_with_cost(self, codec):
        n = codec.get_chunk_count()
        avail = {i: 1 for i in range(n) if i != 1}
        chosen, cost = codec.minimum_to_decode_with_cost({1}, avail)
        assert cost == len(chosen)


class TestLrc:
    @pytest.fixture(scope="class")
    def codec(self):
        # global layout: [c D D D c D D D]: two local groups, each a
        # jerasure k=3 m=1 layer (the reference's canonical example)
        import json
        layers = json.dumps([
            ["cDDD____", {"plugin": "jerasure", "technique": "reed_sol_van"}],
            ["____cDDD", {"plugin": "jerasure", "technique": "reed_sol_van"}],
        ])
        return registry_instance().factory(
            "lrc", {"mapping": "_DDD_DDD", "layers": layers,
                    "runtime": "cpu"})

    def test_geometry(self, codec):
        assert codec.get_chunk_count() == 8
        assert codec.get_data_chunk_count() == 6

    def test_single_erasures_exhaustive(self, codec):
        for i in range(8):
            assert roundtrip(codec, DATA, {i})

    def test_local_repair_stays_in_group(self, codec):
        """Losing a chunk of group 0 must not read group 1 — the whole
        point of locality."""
        avail = set(range(8)) - {1}
        need = codec.minimum_to_decode({1}, avail)
        assert need <= {0, 2, 3}, need

    def test_one_per_group_recovers(self, codec):
        assert roundtrip(codec, DATA, {1, 5})

    def test_two_in_one_group_fails_cleanly(self, codec):
        assert not roundtrip(codec, DATA, {1, 2})

    def test_decode_concat_roundtrip(self, codec):
        n = codec.get_chunk_count()
        enc = codec.encode(set(range(n)), DATA)
        out = codec.decode_concat({i: enc[i] for i in range(n) if i != 2})
        assert out[:len(DATA)] == DATA


class TestClay:
    @pytest.fixture(scope="class", params=[(4, 2), (2, 2), (4, 4)])
    def codec(self, request):
        k, m = request.param
        return registry_instance().factory(
            "clay", {"k": str(k), "m": str(m), "runtime": "cpu"})

    def test_sub_chunk_count(self, codec):
        q, t = codec.q, codec.t
        assert codec.get_sub_chunk_count() == q ** t
        assert q * t == codec.k + codec.m

    def test_single_erasures_exhaustive(self, codec):
        n = codec.get_chunk_count()
        for i in range(n):
            assert roundtrip(codec, DATA, {i})

    def test_m_erasures_exhaustive(self, codec):
        """MDS: every m-failure pattern decodes."""
        n = codec.get_chunk_count()
        for combo in combinations(range(n), codec.m):
            assert roundtrip(codec, DATA, set(combo)), combo

    def test_systematic(self, codec):
        """Data chunks concatenate back to the input (systematic code)."""
        n = codec.get_chunk_count()
        enc = codec.encode(set(range(n)), DATA)
        joined = b"".join(enc[i] for i in range(codec.k))
        assert joined[:len(DATA)] == DATA

    def test_repair_bandwidth_optimal(self, codec):
        """Single-node repair reads alpha/q sub-chunks per helper and
        reconstructs the exact chunk — the MSR property the sub-chunk
        interface exists for."""
        n = codec.get_chunk_count()
        alpha = codec.get_sub_chunk_count()
        enc = codec.encode(set(range(n)), DATA)
        planes = codec._planes()
        for lost in range(n):
            sub_idx = codec.repair_subchunks(lost)
            assert len(sub_idx) == alpha // codec.q
            helper_subchunks = {}
            for i in range(n):
                if i == lost:
                    continue
                arr = np.frombuffer(enc[i], dtype=np.uint8)
                per = codec._split(arr)
                helper_subchunks[i] = {
                    planes[si]: per[planes[si]] for si in sub_idx}
            rebuilt = codec.repair(lost, helper_subchunks)
            assert rebuilt == enc[lost], f"node {lost}"


def test_native_runtime_plugin():
    """runtime=native drives the in-repo C SIMD kernels as a first-class
    plugin runtime (the isa-plugin role on device-less hosts),
    bit-identical to the oracle and to the tpu runtime."""
    import numpy as np

    from ceph_tpu.ec import registry_instance

    reg = registry_instance()
    data = bytes(range(256)) * 64
    outs = {}
    for runtime in ("cpu", "native"):
        codec = reg.factory("isa", {"k": "4", "m": "2",
                                    "technique": "cauchy",
                                    "runtime": runtime})
        enc = codec.encode(set(range(6)), data)
        outs[runtime] = enc
        dec = codec.decode({0, 3}, {i: enc[i] for i in (1, 2, 4, 5)})
        assert dec[0] == enc[0] and dec[3] == enc[3]
    assert outs["cpu"] == outs["native"]
