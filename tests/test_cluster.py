"""End-to-end cluster tests on the vstart-style MiniCluster: replicated and
EC pool I/O, failure detection, remap, recovery — the standalone QA tier
(qa/standalone/ analog) over the loopback stack."""

import time

import numpy as np
import pytest

from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    yield c
    c.stop()


def test_cluster_forms(cluster):
    st = cluster.mon.status()
    assert st["num_up_osds"] == 3
    assert st["num_osds"] == 3


def test_replicated_write_read_roundtrip(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=8, size=3)
    io = client.open_ioctx(pool)
    io.write_full("obj-a", b"hello rados")
    assert io.read("obj-a") == b"hello rados"
    io.write("obj-a", b"HELLO", 0)
    assert io.read("obj-a") == b"HELLO rados"
    assert io.stat("obj-a")["size"] == 11
    io.set_omap("obj-a", {"k": b"v"})
    assert io.get_omap("obj-a") == {"k": b"v"}
    io.remove("obj-a")
    with pytest.raises(OSError):
        io.read("obj-a")


def test_replication_reaches_all_members(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=8, size=3)
    io = client.open_ioctx(pool)
    for i in range(10):
        io.write_full(f"o{i}", f"data{i}".encode() * 20)
    time.sleep(0.2)
    # every object's pg members all hold the object
    m = cluster.mon.osdmap
    for i in range(10):
        from ceph_tpu.client.rados import ceph_str_hash_rjenkins
        from ceph_tpu.osd.osdmap import pg_to_pgid
        ps = ceph_str_hash_rjenkins(f"o{i}")
        pg = pg_to_pgid(ps, m.pools[pool].pg_num)
        up, *_ = m.pg_to_up_acting_osds(pool, pg)
        assert len(up) == 3
        for osd_id in up:
            store = cluster.osds[osd_id].store
            assert store.read(f"{pool}.{pg}", f"o{i}") == \
                f"data{i}".encode() * 20, (i, osd_id)


def test_objects_spread_across_pgs(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=16, size=2)
    io = client.open_ioctx(pool)
    for i in range(40):
        io.write_full(f"spread-{i}", b"x")
    time.sleep(0.2)
    used_pgs = set()
    for osd in cluster.osds.values():
        for cid in osd.store.list_collections():
            if cid.startswith(f"{pool}.") and osd.store.list_objects(cid):
                used_pgs.add(cid)
    assert len(used_pgs) > 4  # hash spread over many pgs


def test_ec_pool_write_read_with_tpu_kernels(cluster):
    # 3 osds can hold k=2 m=1
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
    io.write_full("ec-obj", payload)
    got = io.read("ec-obj")
    assert got == payload
    # chunks actually live as shards on distinct osds
    time.sleep(0.2)
    shard_count = 0
    for osd in cluster.osds.values():
        for cid in osd.store.list_collections():
            for oid in (osd.store.list_objects(cid)
                        if cid.startswith(f"{pool}.") else []):
                if oid.startswith("ec-obj:"):
                    shard_count += 1
    assert shard_count == 3  # k+m shards


def test_ec_overwrite_with_smaller_data(cluster):
    """Shrinking WRITEFULL must truncate stale shard tails (advisor finding:
    stale chunk tails corrupted the re-read)."""
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=2, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    big = bytes(range(256)) * 40          # 10240 B
    small = b"tiny payload"               # much smaller rewrite
    io.write_full("shrink", big)
    assert io.read("shrink") == big
    io.write_full("shrink", small)
    assert io.read("shrink") == small


def test_ec_read_survives_shard_loss(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=1, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    payload = b"erasure coded payload " * 100
    io.write_full("victim", payload)
    time.sleep(0.2)
    # remove one shard object directly from its store (EIO injection analog,
    # test-erasure-eio.sh)
    removed = 0
    for osd in cluster.osds.values():
        for cid in list(osd.store.list_collections()):
            if not cid.startswith(f"{pool}."):
                continue
            for oid in list(osd.store.list_objects(cid)):
                if oid.startswith("victim:") and removed == 0:
                    from ceph_tpu.objectstore import Transaction
                    osd.store.apply_transaction(
                        Transaction().remove(cid, oid))
                    removed = 1
    assert removed == 1
    assert io.read("victim") == payload  # decode path reconstructs


def test_osd_down_triggers_remap_and_resend(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    io.write_full("before", b"pre-failure")
    # mark an osd down via mon command (admin path; heartbeats tested apart)
    victim = 0
    cluster.kill_osd(victim)
    res, _ = client.mon_command({"prefix": "osd down", "id": str(victim)})
    assert res == 0
    epoch = cluster.mon.osdmap.epoch
    cluster.wait_for_epoch(epoch)
    client.wait_for_epoch(epoch)
    # i/o continues against the new primaries
    io.write_full("after", b"post-failure")
    assert io.read("after") == b"post-failure"
    assert io.read("before") == b"pre-failure"


def test_recovery_pulls_missing_objects(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, size=3)
    io = client.open_ioctx(pool)
    for i in range(8):
        io.write_full(f"r{i}", f"recover-{i}".encode())
    time.sleep(0.3)
    # start a brand-new osd; nothing on it yet
    cluster.run_osd(3)
    cluster.wait_for_osd_count(4)
    epoch = cluster.mon.osdmap.epoch
    cluster.wait_for_epoch(epoch)
    # out osd.1 so placements shift toward osd.3
    res, _ = client.mon_command({"prefix": "osd out", "id": "1"})
    assert res == 0
    cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
    time.sleep(0.5)  # scan/pull cycle
    m = cluster.mon.osdmap
    from ceph_tpu.client.rados import ceph_str_hash_rjenkins
    from ceph_tpu.osd.osdmap import pg_to_pgid
    missing = 0
    for i in range(8):
        ps = ceph_str_hash_rjenkins(f"r{i}")
        pg = pg_to_pgid(ps, m.pools[pool].pg_num)
        up, primary, _a, _ap = m.pg_to_up_acting_osds(pool, pg)
        store = cluster.osds[primary].store
        try:
            assert store.read(f"{pool}.{pg}", f"r{i}") == \
                f"recover-{i}".encode()
        except KeyError:
            missing += 1
    assert missing == 0, f"{missing}/8 objects not recovered to new primaries"


def test_filestore_osd_restart_keeps_data(tmp_path):
    c = MiniCluster(n_osds=2, ms_type="loopback", store_type="filestore",
                    base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(2)
        client = c.client()
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        io.write_full("durable", b"survives restart")
        time.sleep(0.2)
        # hard-kill and restart an osd: journal replay must restore its state
        c.kill_osd(1)
        c.run_osd(1)
        c.wait_for_osd_count(2)
        store = c.osds[1].store
        found = any(
            store.exists(cid, "durable")
            for cid in store.list_collections())
        assert found, "restarted filestore osd lost its objects (mkfs wipe?)"
    finally:
        c.stop()


def test_cluster_over_real_tcp_sockets():
    c = MiniCluster(n_osds=3, ms_type="async").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("tcp-obj", b"over real sockets")
        assert io.read("tcp-obj") == b"over real sockets"
        ec_pool = c.create_pool(client, pg_num=2, pool_type="erasure",
                                k=2, m=1)
        io2 = client.open_ioctx(ec_pool)
        io2.write_full("tcp-ec", b"ec over tcp " * 50)
        assert io2.read("tcp-ec") == b"ec over tcp " * 50
    finally:
        c.stop()


def test_heartbeat_failure_detection():
    c = MiniCluster(n_osds=3, ms_type="loopback", heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        for osd in c.osds.values():
            osd.ctx.conf.set("osd_heartbeat_interval", 0.1)
            osd.ctx.conf.set("osd_heartbeat_grace", 0.5)
        time.sleep(0.5)  # peers exchange pings
        victim = 2
        c.kill_osd(victim)
        deadline = time.time() + 8
        while time.time() < deadline:
            if c.mon.status()["num_up_osds"] == 2:
                break
            time.sleep(0.05)
        assert c.mon.status()["num_up_osds"] == 2, \
            "mon never marked the dead osd down from peer reports"
        assert not c.mon.osdmap.is_up(victim)
    finally:
        c.stop()


def test_shec_and_clay_pools_end_to_end():
    """The advanced EC plugins drive the same batched OSD data path."""
    c = MiniCluster(n_osds=7, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(7)
        client = c.client(timeout=20.0)
        shec = c.create_pool(client, pg_num=4, pool_type="erasure",
                             plugin="shec", k=4, m=3, c=2)
        io = client.open_ioctx(shec)
        io.write_full("s1", b"shec-on-the-cluster" * 50)
        assert io.read("s1") == b"shec-on-the-cluster" * 50
        clay = c.create_pool(client, pg_num=4, pool_type="erasure",
                             plugin="clay", k=4, m=2)
        io2 = client.open_ioctx(clay)
        io2.write_full("c1", b"clay-coupled-layers" * 64)
        assert io2.read("c1") == b"clay-coupled-layers" * 64
    finally:
        c.stop()


def test_ec_partial_write_rmw(cluster):
    """OP_WRITE at arbitrary offsets on an EC pool round-trips through
    the stripe-aligned read-modify-write pipeline (ECBackend start_rmw)."""
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    base = bytearray(b"A" * 20000)
    io.write_full("rmw", bytes(base))
    # overwrite a range crossing stripe boundaries (stripe_unit 4096,
    # width 8192)
    io.write("rmw", b"B" * 5000, offset=6000)
    base[6000:11000] = b"B" * 5000
    assert io.read("rmw") == bytes(base)
    # extend past the end (object grows, new stripes appear)
    io.write("rmw", b"C" * 7000, offset=19000)
    base = base[:19000] + b"C" * 7000
    assert io.read("rmw") == bytes(base)
    # partial write to a fresh object (zero-filled head)
    io.write("rmw2", b"D" * 100, offset=9000)
    got = io.read("rmw2")
    assert got[:9000] == bytes(9000) and got[9000:] == b"D" * 100


def test_ec_range_read(cluster):
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    payload = bytes(range(256)) * 64          # 16 KiB, 2 stripes
    io.write_full("rr", payload)
    assert io.read("rr", length=100, offset=5000) == payload[5000:5100]
    assert io.read("rr", length=0, offset=9000) == payload[9000:]


def test_ec_corrupt_shard_detected_and_reconstructed(cluster):
    """A flipped byte in a stored shard fails the HashInfo checksum: the
    read reconstructs from the other shards and a repair rewrites the
    bad copy (ECUtil HashInfo semantics)."""
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, pool_type="erasure",
                               k=2, m=1)
    io = client.open_ioctx(pool)
    payload = b"integrity-matters" * 400
    io.write_full("crc", payload)
    time.sleep(0.2)
    # find a stored shard and flip a byte behind the OSD's back
    from ceph_tpu.client.rados import ceph_str_hash_rjenkins
    from ceph_tpu.osd.osdmap import pg_to_pgid
    m = cluster.mon.osdmap
    pg = pg_to_pgid(ceph_str_hash_rjenkins("crc"), m.pools[pool].pg_num)
    up, _p, _a, _ap = m.pg_to_up_acting_osds(pool, pg)
    victim = cluster.osds[up[0]]
    cid = f"{pool}.{pg}"
    blob = bytearray(victim.store.read(cid, "crc:0"))
    blob[7] ^= 0xFF
    from ceph_tpu.objectstore import Transaction
    t = Transaction().truncate(cid, "crc:0", 0).write(cid, "crc:0", 0,
                                                      bytes(blob))
    victim.store.apply_transaction(t)   # corrupt WITHOUT updating hinfo
    # the read must still return correct bytes (reconstructed)
    assert io.read("crc") == payload
    # and the repair eventually rewrites the shard with a valid checksum
    from ceph_tpu.osd.ec_util import HashInfo
    deadline = time.time() + 10
    while time.time() < deadline:
        cur = victim.store.read(cid, "crc:0")
        hinfo = victim.store.getattr(cid, "crc:0", "hinfo")
        if HashInfo.matches(cur, hinfo) and cur != bytes(blob):
            break
        time.sleep(0.1)
    cur = victim.store.read(cid, "crc:0")
    assert HashInfo.matches(cur, victim.store.getattr(cid, "crc:0",
                                                      "hinfo"))
    assert cur != bytes(blob), "corrupt shard never repaired"


def test_ec_bitmatrix_technique_pool(cluster):
    """Bitmatrix techniques need chunk % w == 0: the stripe unit rounds
    up to the codec's alignment quantum (w=7 for liberation)."""
    client = cluster.client()
    pool = cluster.create_pool(client, pg_num=4, pool_type="erasure",
                               k=2, m=2, technique="liberation")
    io = client.open_ioctx(pool)
    payload = b"w-aligned-stripes" * 700
    io.write_full("lb", payload)
    assert io.read("lb") == payload
    io.write("lb", b"Z" * 3000, offset=5000)
    want = payload[:5000] + b"Z" * 3000 + payload[8000:]
    assert io.read("lb") == want


def test_health_command(cluster):
    client = cluster.client()
    import json
    rc, out = client.mon_command({"prefix": "health"})
    assert rc == 0
    h = json.loads(out)
    assert h["status"] == "HEALTH_OK" and h["checks"] == []
    cluster.kill_osd(2)
    rc, _ = client.mon_command({"prefix": "osd down", "id": 2})
    assert rc == 0
    rc, out = client.mon_command({"prefix": "health"})
    h = json.loads(out)
    assert h["status"] == "HEALTH_WARN"
    osd_down = next(c for c in h["checks"] if c["check"] == "OSD_DOWN")
    assert osd_down["osds"] == [2]
    assert "summary" in osd_down
    # the detail variant carries per-item lines
    rc, out = client.mon_command({"prefix": "health detail"})
    h = json.loads(out)
    dd = next(c for c in h["checks"] if c["check"] == "OSD_DOWN")
    assert dd["detail"] == ["osd.2 is down"]
