"""Static lock-order analysis (check family ``lock-order``).

From every function's acquisition events (``with``/``acquire()``
sites, each annotated with the lock stack held there) and call sites,
build the may-hold-A-while-taking-B graph:

* ``with A: with B`` records A->B directly;
* ``with A: f()`` records A->M for every lock M in f's transitive
  *effective acquire* set (interprocedural fixpoint over the
  best-effort call graph).

The static graph is unioned with the runtime ``lockdep`` graph (a
``lockdep.export_graph()`` snapshot, ``--runtime-graph``), then every
strongly connected component with more than one lock is reported as a
cycle, with one witness site per edge — the static witness spells out
the hold-site -> call-chain -> acquire-site path.

An edge is suppressed by ``# analysis: allow[lock-order] -- reason``
on its hold/call site line (the reference's per-site
``lockdep_will_lock`` escape hatch).
"""

from __future__ import annotations

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, normalize_name


def _edge_suppressed(index: TreeIndex, mod, line: int) -> bool:
    from ceph_tpu import analysis
    return analysis._suppression(
        index, mod.relpath, line, "lock-order") is not None


def effective_acquires(index: TreeIndex):
    """Fixpoint: fn -> set of lock names it may acquire transitively.
    Also returns a cause map for witness reconstruction:
    cause[(fn, lock)] = ("direct", relpath, line)
                      | ("call", callee_fn, relpath, line)."""
    funcs = sorted(index.all_functions(), key=lambda f: f.qualname)
    eff: dict = {f: set() for f in funcs}
    cause: dict = {}
    for f in funcs:
        for ev in f.acq_events:
            if ev.lock not in eff[f]:
                eff[f].add(ev.lock)
                cause[(f, ev.lock)] = ("direct", f.module.relpath,
                                       ev.line)
    resolved: dict = {}
    for f in funcs:
        # "nested" call sites mark where a closure/lambda is DEFINED,
        # not where it runs: it executes later, usually on another
        # thread with an empty held stack, so neither its acquire set
        # nor held-edges may flow through the definition site.  (Its
        # own body still contributes its own events: all_functions()
        # yields nested functions directly.  A local helper that IS
        # called synchronously also has a normal ("name", ..) site.)
        resolved[f] = [(index.resolve_call(f, cs.spec), cs)
                       for cs in f.call_sites
                       if cs.spec[0] != "nested"]
    changed = True
    while changed:
        changed = False
        for f in funcs:
            for g, cs in resolved[f]:
                if g is None or g not in eff:
                    continue
                for m in eff[g]:
                    if m not in eff[f]:
                        eff[f].add(m)
                        cause[(f, m)] = ("call", g, f.module.relpath,
                                         cs.line)
                        changed = True
    return eff, cause, resolved


def _witness(cause, f, lock, limit: int = 6) -> str:
    """hold-to-acquire chain for 'f eventually acquires lock'."""
    hops = []
    cur = f
    while limit:
        limit -= 1
        c = cause.get((cur, lock))
        if c is None:
            break
        if c[0] == "direct":
            hops.append(f"{c[1]}:{c[2]} acquires")
            break
        hops.append(f"{c[2]}:{c[3]} calls {c[1].qualname}")
        cur = c[1]
    return " -> ".join(hops) if hops else "(unknown)"


def build_graph(index: TreeIndex, runtime_graph: dict | None = None):
    """-> {(a, b): site_str} over normalized lock names."""
    eff, cause, resolved = effective_acquires(index)
    edges: dict = {}

    def add(a: str, b: str, site: str) -> None:
        if a != b and (a, b) not in edges:
            edges[(a, b)] = site

    for f in sorted(eff, key=lambda x: x.qualname):
        mod = f.module
        for ev in f.acq_events:
            if _edge_suppressed(index, mod, ev.line):
                continue
            for h in ev.held:
                add(h, ev.lock,
                    f"{mod.relpath}:{ev.line} in {f.qualname}")
        for g, cs in resolved[f]:
            if g is None or not cs.held:
                continue
            if _edge_suppressed(index, mod, cs.line):
                continue
            for m in eff.get(g, ()):
                site = (f"{mod.relpath}:{cs.line} in {f.qualname} "
                        f"calls {g.qualname}; "
                        f"{_witness(cause, g, m)}")
                for h in cs.held:
                    add(h, m, site)
    if runtime_graph:
        for e in runtime_graph.get("edges", []):
            a = normalize_name(str(e.get("a", "")))
            b = normalize_name(str(e.get("b", "")))
            if a and b:
                site = str(e.get("site", "")).strip().splitlines()
                add(a, b, "runtime: " + (site[0].strip() if site
                                         else "(no site)"))
    return edges


def _sccs(nodes, succ):
    """Tarjan, iterative; yields SCCs as lists."""
    index_of: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    counter = [0]
    out = []
    for root in sorted(nodes):
        if root in index_of:
            continue
        work = [(root, iter(sorted(succ.get(root, ()))))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index_of[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index_of[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _shortest_cycle(comp, succ):
    """BFS a minimal cycle inside one SCC; -> [n0, n1, ..., n0]."""
    comp_set = set(comp)
    best = None
    for start in sorted(comp):
        prev: dict = {}
        queue, seen, found = [start], {start}, None
        while queue and found is None:
            nxt = []
            for v in queue:
                for w in sorted(succ.get(v, ())):
                    if w == start:
                        found = v
                        break
                    if w in comp_set and w not in seen:
                        seen.add(w)
                        prev[w] = v
                        nxt.append(w)
                if found is not None:
                    break
            queue = nxt
        if found is not None:
            path = [found]
            while path[-1] != start:
                path.append(prev[path[-1]])
            path.reverse()
            path.append(start)
            if best is None or len(path) < len(best):
                best = path
            if len(best) == 3:      # A -> B -> A: minimal possible
                break
    return best


def format_cycle(path, edges) -> str:
    """Render a cycle with one witness per edge, both directions
    included — the dual-witness message lockdep raises with."""
    parts = []
    for a, b in zip(path, path[1:]):
        parts.append(f"{a} -> {b}  [{edges.get((a, b), '(no site)')}]")
    return "lock-order cycle: " + "; ".join(parts)


def check(index: TreeIndex, runtime_graph: dict | None = None):
    edges = build_graph(index, runtime_graph)
    succ: dict = {}
    nodes: set = set()
    for (a, b) in edges:
        succ.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    findings = []
    for comp in _sccs(nodes, succ):
        if len(comp) < 2:
            continue
        path = _shortest_cycle(comp, succ) or sorted(comp) + [
            sorted(comp)[0]]
        msg = format_cycle(path, edges)
        # anchor at the first static witness so an inline suppression
        # (or a fix) at that site owns the finding
        anchor_path, anchor_line = "(runtime)", 0
        for a, b in zip(path, path[1:]):
            site = edges.get((a, b), "")
            if site and not site.startswith("runtime:"):
                loc = site.split(" ", 1)[0]
                if ":" in loc:
                    p, _, ln = loc.rpartition(":")
                    if ln.isdigit():
                        anchor_path, anchor_line = p, int(ln)
                        break
        # the node set rides the code so distinct cycles keep distinct
        # baseline keys even when anchored at ("(runtime)", 0) —
        # Finding.key() excludes the (witness-bearing, volatile)
        # message, and node names are line-stable
        code = "cycle:" + "+".join(sorted(set(path)))
        findings.append(Finding("lock-order", anchor_path, anchor_line,
                                code, msg))
    findings.sort(key=lambda f: f.message)
    return findings
