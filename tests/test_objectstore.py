"""ObjectStore test suite run across all backends (ceph_test_objectstore
pattern: one suite, every store), plus FileStore journal-replay/torn-write
crash tests and the KV layer."""

import os

import pytest

from ceph_tpu.objectstore import (
    LogDB, MemDB, Transaction, create_objectstore)
from ceph_tpu.objectstore.kv import KVTransaction


@pytest.fixture(params=["memstore", "filestore", "bluestore"])
def store(request, tmp_path):
    s = create_objectstore(request.param, str(tmp_path / "store"))
    s.mkfs()
    s.mount()
    yield s
    s.umount()


def test_basic_write_read(store):
    t = (Transaction()
         .create_collection("pg1")
         .write("pg1", "obj", 0, b"hello world"))
    store.apply_transaction(t)
    assert store.read("pg1", "obj") == b"hello world"
    assert store.read("pg1", "obj", 6, 5) == b"world"
    assert store.stat("pg1", "obj")["size"] == 11
    assert store.exists("pg1", "obj")
    assert not store.exists("pg1", "nope")


def test_write_extends_with_zeros(store):
    store.apply_transaction(
        Transaction().create_collection("c").write("c", "o", 8, b"xy"))
    assert store.read("c", "o") == b"\x00" * 8 + b"xy"


def test_zero_truncate_remove(store):
    store.apply_transaction(
        Transaction().create_collection("c").write("c", "o", 0, b"a" * 16))
    store.apply_transaction(Transaction().zero("c", "o", 4, 8))
    assert store.read("c", "o") == b"aaaa" + b"\x00" * 8 + b"aaaa"
    store.apply_transaction(Transaction().truncate("c", "o", 4))
    assert store.read("c", "o") == b"aaaa"
    store.apply_transaction(Transaction().remove("c", "o"))
    assert not store.exists("c", "o")


def test_omap_and_attrs(store):
    t = (Transaction().create_collection("c")
         .touch("c", "o")
         .omap_setkeys("c", "o", {"k1": b"v1", "k2": b"v2"})
         .setattr("c", "o", "_", b"objinfo"))
    store.apply_transaction(t)
    assert store.omap_get("c", "o") == {"k1": b"v1", "k2": b"v2"}
    assert store.getattr("c", "o", "_") == b"objinfo"
    store.apply_transaction(Transaction().omap_rmkeys("c", "o", ["k1"]))
    assert store.omap_get("c", "o") == {"k2": b"v2"}


def test_clone_and_listing(store):
    store.apply_transaction(
        Transaction().create_collection("c")
        .write("c", "src", 0, b"data").omap_setkeys("c", "src", {"a": b"1"}))
    store.apply_transaction(Transaction().clone("c", "src", "dst"))
    assert store.read("c", "dst") == b"data"
    assert store.omap_get("c", "dst") == {"a": b"1"}
    assert store.list_objects("c") == ["dst", "src"]
    assert store.list_collections() == ["c"]


def test_missing_collection_raises(store):
    with pytest.raises(KeyError):
        store.read("nope", "o")
    with pytest.raises(KeyError):
        store.apply_transaction(Transaction().write("nope", "o", 0, b"x"))


def test_on_commit_callback(store):
    fired = []
    store.queue_transactions(
        [Transaction().create_collection("c").write("c", "o", 0, b"z")],
        on_commit=lambda: fired.append(True))
    assert fired == [True]


def test_transaction_codec_roundtrip():
    t = (Transaction().create_collection("c").write("c", "o", 8, b"abc")
         .omap_setkeys("c", "o", {"k": b"v"}).truncate("c", "o", 4)
         .clone("c", "o", "o2").setattr("c", "o", "_", b"i"))
    back = Transaction.decode(t.encode())
    assert len(back) == len(t)
    for a, b in zip(t.ops, back.ops):
        assert (a.op, a.cid, a.oid, a.offset, a.length, a.data, a.keys,
                a.rmkeys, a.dest, a.name) == \
               (b.op, b.cid, b.oid, b.offset, b.length, b.data, b.keys,
                b.rmkeys, b.dest, b.name)


# -- FileStore durability ----------------------------------------------------

def test_filestore_journal_replay(tmp_path):
    path = str(tmp_path / "fs")
    s = create_objectstore("filestore", path)
    s.mkfs()
    s.mount()
    s.apply_transaction(
        Transaction().create_collection("pg1").write("pg1", "o", 0, b"abc"))
    # crash without umount: journal must carry the state
    s2 = create_objectstore("filestore", path)
    s2.mount()
    assert s2.read("pg1", "o") == b"abc"
    s2.umount()


def test_filestore_checkpoint_and_replay(tmp_path):
    path = str(tmp_path / "fs")
    s = create_objectstore("filestore", path)
    s.mkfs()
    s.mount()
    s.apply_transaction(
        Transaction().create_collection("c").write("c", "a", 0, b"1"))
    s.checkpoint()
    s.apply_transaction(Transaction().write("c", "b", 0, b"2"))
    s2 = create_objectstore("filestore", path)
    s2.mount()
    assert s2.read("c", "a") == b"1"
    assert s2.read("c", "b") == b"2"
    s2.umount()


def test_filestore_torn_journal_tail_ignored(tmp_path):
    path = str(tmp_path / "fs")
    s = create_objectstore("filestore", path)
    s.mkfs()
    s.mount()
    s.apply_transaction(
        Transaction().create_collection("c").write("c", "good", 0, b"ok"))
    s.umount()
    # simulate a torn write: append garbage half-frame
    with open(os.path.join(path, "journal"), "ab") as f:
        f.write(b"\xff\xff\xff\x7f\x00\x00")
    s2 = create_objectstore("filestore", path)
    s2.mount()   # replay must stop at the torn tail, not crash
    assert s2.read("c", "good") == b"ok"
    s2.umount()


# -- KV ----------------------------------------------------------------------

def test_memdb_transactions():
    db = MemDB()
    t = db.get_transaction().set("p", "k1", b"v1").set("p", "k2", b"v2")
    db.submit_transaction(t)
    db.submit_transaction(db.get_transaction().rmkey("p", "k1"))
    assert db.get("p", "k1") is None
    assert db.get("p", "k2") == b"v2"
    assert db.get_range("p") == {"k2": b"v2"}


def test_logdb_durability_and_compaction(tmp_path):
    path = str(tmp_path / "kv")
    db = LogDB(path)
    db.open()
    db.submit_transaction(db.get_transaction().set("m", "epoch", b"1"))
    db.submit_transaction(db.get_transaction().set("m", "epoch", b"2"))
    db.close()
    db2 = LogDB(path)
    db2.open()
    assert db2.get("m", "epoch") == b"2"
    db2.compact()
    db2.submit_transaction(db2.get_transaction().set("m", "extra", b"x"))
    db2.close()
    db3 = LogDB(path)
    db3.open()
    assert db3.get("m", "epoch") == b"2"
    assert db3.get("m", "extra") == b"x"
    db3.close()


def test_kv_transaction_codec():
    t = KVTransaction().set("a", "b", b"c").rmkey("d", "e")
    back = KVTransaction.decode(t.encode())
    assert back.sets == [("a", "b", b"c")]
    assert back.rms == [("d", "e")]



def test_bluestore_restart_durability(tmp_path):
    """Data lives on the block file, metadata in the KV: a remount sees
    everything, and reads come from disk, not RAM."""
    from ceph_tpu.objectstore import create_objectstore
    path = str(tmp_path / "bs")
    s = create_objectstore("bluestore", path)
    s.mkfs_if_needed()
    s.mount()
    t = (Transaction().create_collection("1.0")
         .write("1.0", "a", 0, b"durable" * 1000)
         .setattr("1.0", "a", "_v", b"7.1")
         .omap_setkeys("1.0", "a", {"k": b"v"}))
    s.apply_transaction(t)
    s.umount()
    s2 = create_objectstore("bluestore", path)
    s2.mkfs_if_needed()   # must NOT wipe an existing store
    s2.mount()
    assert s2.read("1.0", "a") == b"durable" * 1000
    assert s2.getattr("1.0", "a", "_v") == b"7.1"
    assert s2.omap_get("1.0", "a") == {"k": b"v"}
    s2.umount()


def test_bluestore_allocator_reuses_freed_blocks(tmp_path):
    from ceph_tpu.objectstore import create_objectstore
    path = str(tmp_path / "bs2")
    s = create_objectstore("bluestore", path)
    s.mkfs_if_needed()
    s.mount()
    s.apply_transaction(Transaction().create_collection("c"))
    for i in range(8):
        s.apply_transaction(
            Transaction().write("c", f"o{i}", 0, b"x" * 8192))
    import os
    size_before = os.path.getsize(f"{path}/block")
    for i in range(8):
        s.apply_transaction(Transaction().remove("c", f"o{i}"))
    for i in range(8):
        s.apply_transaction(
            Transaction().write("c", f"n{i}", 0, b"y" * 8192))
    s.umount()
    # freed extents were reused: the block file did not double
    assert os.path.getsize(f"{path}/block") <= size_before + 8192


def test_bluestore_cluster_end_to_end(tmp_path):
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=3, ms_type="loopback", store_type="bluestore",
                    base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=40.0)  # generous: suite runs fully loaded
        pool = c.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        io.write_full("b", b"bluestore-backed" * 100)
        assert io.read("b") == b"bluestore-backed" * 100
        ec = c.create_pool(client, pg_num=4, pool_type="erasure",
                           k=2, m=1)
        io2 = client.open_ioctx(ec)
        io2.write_full("e", b"E" * 9000)
        io2.write("e", b"Z" * 2000, offset=4000)
        want = b"E" * 4000 + b"Z" * 2000 + b"E" * 3000
        assert io2.read("e") == want
    finally:
        c.stop()


def test_bluestore_crash_remount_allocator_safe(tmp_path):
    """Hard-kill crash model: reopen WITHOUT umount.  The rebuilt
    allocator must not hand out live blocks, and committed overwrites
    must be intact (COW + fsck-style free-list rebuild)."""
    from ceph_tpu.objectstore import create_objectstore
    path = str(tmp_path / "bs3")
    s = create_objectstore("bluestore", path)
    s.mkfs_if_needed()
    s.mount()
    s.apply_transaction(Transaction().create_collection("c")
                        .write("c", "a", 0, b"A" * 8192))
    s.apply_transaction(Transaction().write("c", "a", 100, b"patch"))
    # simulate a crash: drop the handles without umount bookkeeping
    s._f.close()
    s._db.close()
    s2 = create_objectstore("bluestore", path)
    s2.mkfs_if_needed()
    s2.mount()
    want = b"A" * 100 + b"patch" + b"A" * (8192 - 105)
    assert s2.read("c", "a") == want
    # new writes after the crash must not corrupt the survivor
    s2.apply_transaction(Transaction().write("c", "b", 0, b"B" * 8192))
    assert s2.read("c", "a") == want
    assert s2.read("c", "b") == b"B" * 8192
    s2.umount()


def test_bluestore_rmcoll_purges_and_zero_punches_holes(tmp_path):
    import os as _os
    from ceph_tpu.objectstore import create_objectstore
    path = str(tmp_path / "bs4")
    s = create_objectstore("bluestore", path)
    s.mkfs_if_needed()
    s.mount()
    s.apply_transaction(Transaction().create_collection("c")
                        .write("c", "o", 0, b"x" * 16384))
    # zero the middle: full blocks become holes, not zero-filled disk
    size_before = _os.path.getsize(f"{path}/block")
    s.apply_transaction(Transaction().zero("c", "o", 4096, 8192))
    assert s.read("c", "o") == b"x" * 4096 + bytes(8192) + b"x" * 4096
    assert _os.path.getsize(f"{path}/block") <= size_before + 2 * 4096
    # rmcoll purges objects; recreating the collection finds it empty
    s.apply_transaction(Transaction().remove_collection("c"))
    s.apply_transaction(Transaction().create_collection("c"))
    assert not s.exists("c", "o")
    assert s.list_objects("c") == []
    s.umount()


def test_bluestore_remove_recreate_one_txn(tmp_path):
    """Recovery's replace-wholesale push removes and rewrites the same
    object in ONE transaction; the KV batch (sets-then-rms) must not
    let the remove eat the recreate.  Same for collections."""
    from ceph_tpu.objectstore import create_objectstore
    path = str(tmp_path / "bs5")
    s = create_objectstore("bluestore", path)
    s.mkfs_if_needed()
    s.mount()
    s.apply_transaction(Transaction().create_collection("c")
                        .write("c", "o", 0, b"old" * 2000))
    s.apply_transaction(Transaction()
                        .remove("c", "o")
                        .write("c", "o", 0, b"new")
                        .setattr("c", "o", "_v", b"9.9"))
    assert s.read("c", "o") == b"new"
    assert s.getattr("c", "o", "_v") == b"9.9"
    s.apply_transaction(Transaction()
                        .remove_collection("c")
                        .create_collection("c")
                        .write("c", "p", 0, b"fresh"))
    assert s.list_objects("c") == ["p"]
    # survives a remount (the KV really holds the final state)
    s.umount()
    s2 = create_objectstore("bluestore", path)
    s2.mkfs_if_needed()
    s2.mount()
    assert not s2.exists("c", "o")
    assert s2.read("c", "p") == b"fresh"
    assert s2.list_objects("c") == ["p"]
    s2.umount()
