"""Native (C) single-core baseline kernels, compiled on first use.

These are the honest CPU yardsticks bench.py compares the TPU kernels
against (BASELINE.md rows): an ISA-L-class split-nibble GF(2^8) encode and a
scalar straw2 ``crush_do_rule`` (semantics of src/crush/mapper.c:900, ported
from the in-repo oracle ``crush.mapper_ref`` and cross-validated in
tests/test_native.py).

The shared library builds with the system C compiler at first call and is
cached next to the source keyed by a source hash; no pip/cmake involved.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "baseline.c")
# analysis: allow[bare-lock] -- import-time ctypes loader guard; leaf
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None


class NativeUnavailable(RuntimeError):
    pass


def _build() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_DIR, f"_baseline_{tag}.so")
    if os.path.exists(out):
        return out
    for cc in ("cc", "gcc", "clang"):
        try:
            subprocess.run(
                [cc, "-O3", "-march=native", "-funroll-loops", "-shared",
                 "-fPIC", "-o", out + ".tmp", _SRC],
                check=True, capture_output=True, timeout=120)
            os.replace(out + ".tmp", out)
            return out
        except (OSError, subprocess.SubprocessError):
            continue
    raise NativeUnavailable("no working C compiler found")


def lib() -> ctypes.CDLL:
    global _LIB
    with _LOCK:
        if _LIB is None:
            so = _build()
            L = ctypes.CDLL(so)
            L.ec_encode_c.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_long, ctypes.c_long]
            L.ec_encode_c.restype = None
            L.crush_init.argtypes = [ctypes.POINTER(ctypes.c_int64)]
            L.crush_init.restype = ctypes.c_void_p
            L.crush_free.argtypes = [ctypes.c_void_p]
            L.crush_free.restype = None
            L.crush_do_rule_c.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_int]
            L.crush_do_rule_c.restype = ctypes.c_int
            L.crush_batch_c.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_long, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32)]
            L.crush_batch_c.restype = ctypes.c_int
            _LIB = L
        return _LIB


def ec_encode_native(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Single-core C encode.  matrix (m, k) uint8; data (stripes, k, chunk)
    uint8 C-contiguous.  Returns parity (stripes, m, chunk)."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = matrix.shape
    stripes, k2, chunk = data.shape
    assert k2 == k
    parity = np.empty((stripes, m, chunk), dtype=np.uint8)
    lib().ec_encode_c(
        matrix.ctypes.data_as(ctypes.c_char_p), k, m,
        data.ctypes.data_as(ctypes.c_char_p),
        parity.ctypes.data_as(ctypes.c_char_p), stripes, chunk)
    return parity


_TUNABLE_FIELDS = (
    "choose_local_tries", "choose_local_fallback_tries", "choose_total_tries",
    "chooseleaf_descend_once", "chooseleaf_vary_r", "chooseleaf_stable",
    "straw_calc_version")

CRUSH_ITEM_NONE = 0x7FFFFFFF


def _map_blob(crush_map) -> np.ndarray:
    """Serialize a crush.types.CrushMap into the int64 blob crush_init eats."""
    from ceph_tpu.crush.ln_table import lh_table, ll_table, rh_table
    from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW, CRUSH_BUCKET_TREE

    words: list[int] = [0xCB02, crush_map.max_devices,
                       crush_map.max_buckets, crush_map.max_rules]
    words += [getattr(crush_map.tunables, f) for f in _TUNABLE_FIELDS]
    for b in crush_map.buckets:
        if b is None:
            words.append(0)
            continue
        words += [1, b.id, b.type, b.alg, b.size]
        words += list(b.items)
        if b.alg == CRUSH_BUCKET_STRAW:
            words += list(b.straws)  # straw draws use straws, not weights
        else:
            words += list(b.item_weights) if b.item_weights \
                else [b.item_weight] * b.size
        if b.alg == CRUSH_BUCKET_TREE:
            words += [len(b.node_weights)]
            words += list(b.node_weights)
    for r in crush_map.rules:
        if r is None:
            words.append(0)
            continue
        words += [1, len(r.steps)]
        for s in r.steps:
            words += [s.op, s.arg1, s.arg2]
    words += [int(v) for v in rh_table()]
    words += [int(v) for v in lh_table()]
    words += [int(v) for v in ll_table()]
    return np.asarray(
        [w - (1 << 64) if w >= (1 << 63) else w for w in words],
        dtype=np.int64)


class CrushBaseline:
    """Scalar C crush_do_rule over a frozen CrushMap (one core, one x at a
    time) — the single-core number the batched TPU engine must beat."""

    def __init__(self, crush_map):
        self._blob = _map_blob(crush_map)
        self._h = lib().crush_init(
            self._blob.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if not self._h:
            raise NativeUnavailable("crush_init failed")
        self.result_max_limit = 64

    def close(self) -> None:
        if getattr(self, "_h", None):
            lib().crush_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def do_rule(self, ruleno: int, x: int, result_max: int,
                weights: list[int] | np.ndarray) -> list[int]:
        w = np.ascontiguousarray(weights, dtype=np.uint32)
        out = np.full(result_max, CRUSH_ITEM_NONE, dtype=np.int32)
        n = lib().crush_do_rule_c(
            self._h, ruleno, x & 0xFFFFFFFF,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), result_max,
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(w))
        if n < 0:
            raise ValueError(
                f"result_max {result_max} exceeds the C baseline's "
                f"working-set capacity ({self.result_max_limit})")
        return [int(v) for v in out[:n]]

    def do_rule_batch(self, ruleno: int, xs: np.ndarray, result_max: int,
                      weights: np.ndarray) -> np.ndarray:
        """(nx, result_max) int32, NONE-padded — the bulk-remap workload."""
        xs = np.ascontiguousarray(xs, dtype=np.uint32)
        w = np.ascontiguousarray(weights, dtype=np.uint32)
        out = np.empty((len(xs), result_max), dtype=np.int32)
        rc = lib().crush_batch_c(
            self._h, ruleno,
            xs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(xs),
            result_max,
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(w),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc < 0:
            raise ValueError(
                f"result_max {result_max} exceeds the C baseline's "
                f"working-set capacity ({self.result_max_limit})")
        return out
