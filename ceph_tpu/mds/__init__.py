"""ceph-mds analog: the CephFS metadata tier (src/mds/)."""

from .server import MDSDaemon, MClientRequest, MClientReply  # noqa: F401

__all__ = ["MDSDaemon", "MClientRequest", "MClientReply"]
