"""mClock scheduler + sharded op queue (osd/mClock*, OSD.h ShardedOpWQ
analog): tag math, class arbitration, per-key FIFO, and the OSD wired
through it."""

import threading
import time

from ceph_tpu.osd.op_queue import (
    ClassInfo, MClockQueue, ShardedOpQueue)


def test_fifo_within_class():
    q = MClockQueue({"client": ClassInfo(weight=10.0)})
    for i in range(20):
        q.enqueue("client", i, now=0.0)
    got = [q.dequeue(now=0.0)[1] for _ in range(20)]
    assert got == list(range(20))


def test_weight_dominant_class_drains_first():
    q = MClockQueue({"heavy": ClassInfo(weight=100.0),
                     "light": ClassInfo(weight=1.0)})
    for i in range(10):
        q.enqueue("heavy", f"h{i}", now=0.0)
        q.enqueue("light", f"l{i}", now=0.0)
    first10 = [q.dequeue(now=0.0)[0] for _ in range(10)]
    # heavy p-tags advance by 1/100, light by 1: heavy runs 10:1
    assert first10.count("heavy") >= 9


def test_reservation_preempts_weight():
    q = MClockQueue({"client": ClassInfo(weight=100.0),
                     "recovery": ClassInfo(reservation=10.0, weight=1.0)})
    q.enqueue("client", "c", now=0.0)
    q.enqueue("recovery", "r", now=0.0)
    # at t=0 the recovery reservation tag (0.1) is not yet due
    assert q.dequeue(now=0.0)[0] == "client"
    q.enqueue("client", "c2", now=0.15)
    # at t=0.2 the reservation is due: recovery preempts the heavier class
    assert q.dequeue(now=0.2)[0] == "recovery"


def test_limit_caps_class_until_others_drain():
    q = MClockQueue({"client": ClassInfo(weight=100.0),
                     "scrub": ClassInfo(weight=5.0, limit=100.0)})
    for i in range(5):
        q.enqueue("client", f"c{i}", now=0.0)
        q.enqueue("scrub", f"s{i}", now=0.0)
    order = [q.dequeue(now=0.0)[0] for _ in range(10)]
    # at frozen t=0 scrub's limit tag (0.01) never becomes eligible:
    # clients drain first, scrubs only via the work-conserving fallback
    assert order[:5] == ["client"] * 5
    assert order[5:] == ["scrub"] * 5


def test_idle_class_tag_reset():
    q = MClockQueue({"a": ClassInfo(weight=1.0)})
    q.enqueue("a", 1, now=0.0)
    assert q.dequeue(now=0.0)[1] == 1
    # long idle gap: tags must restart from now, not accumulate debt
    q.enqueue("a", 2, now=100.0)
    item = q.dequeue(now=100.0)[1]
    assert item == 2


def test_sharded_queue_preserves_per_key_order():
    seen: dict[str, list] = {"k0": [], "k1": []}
    lock = threading.Lock()

    def handler(klass, item):
        key, seq = item
        with lock:
            seen[key].append(seq)

    wq = ShardedOpQueue(handler, n_shards=2, name="test")
    try:
        for seq in range(200):
            wq.enqueue("k0", "client", ("k0", seq))
            wq.enqueue("k1", "client", ("k1", seq))
        deadline = time.time() + 5
        while time.time() < deadline:
            with lock:
                if len(seen["k0"]) == 200 and len(seen["k1"]) == 200:
                    break
            time.sleep(0.01)
        assert seen["k0"] == list(range(200))
        assert seen["k1"] == list(range(200))
    finally:
        wq.shutdown()


def test_handler_exception_does_not_kill_worker():
    done = threading.Event()

    def handler(klass, item):
        if item == "boom":
            raise RuntimeError("injected")
        done.set()

    wq = ShardedOpQueue(handler, n_shards=1, name="test")
    try:
        wq.enqueue("k", "client", "boom")
        wq.enqueue("k", "client", "ok")
        assert done.wait(timeout=5.0), "worker died on handler exception"
    finally:
        wq.shutdown()


def test_cluster_io_rides_the_mclock_queue():
    """Default osd_op_queue=mclock: client + EC I/O flow through the
    sharded queue end-to-end."""
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=6, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(6)
        assert all(o.opwq is not None for o in c.osds.values())
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=8, size=3)
        io = client.open_ioctx(pool)
        for i in range(12):
            io.write_full(f"q{i}", f"mclock-{i}".encode() * 30)
        for i in range(12):
            assert io.read(f"q{i}") == f"mclock-{i}".encode() * 30
        ec = c.create_pool(client, pg_num=4, pool_type="erasure", k=4, m=2)
        io2 = client.open_ioctx(ec)
        io2.write_full("eq", b"ec-through-the-queue" * 40)
        assert io2.read("eq") == b"ec-through-the-queue" * 40
    finally:
        c.stop()


def test_per_client_qos_limit_and_fairness():
    """dmclock client classes: a limited client is capped while an
    unlimited one flows; two equal-weight clients share service
    (mClockClientQueue analog)."""
    from ceph_tpu.osd.op_queue import ClassInfo, MClockQueue

    q = MClockQueue(classes={}, client_template=ClassInfo(
        reservation=0.0, weight=10.0, limit=0.0))
    # client.slow gets an explicit 2 ops/s limit by pre-creating its class
    q.enqueue("client.slow", "s0", now=0.0)
    q._classes["client.slow"].info = ClassInfo(weight=10.0, limit=2.0)
    q._classes["client.slow"].l_tag = 0.5
    for i in range(4):
        q.enqueue("client.slow", f"s{i+1}", now=0.0)
        q.enqueue("client.fast", f"f{i}", now=0.0)
    served = []
    t = 0.0
    while len(q):
        got = q.dequeue(now=t)
        served.append(got[0])
        t += 0.01   # 100 ops/s service rate
    # in the first ~40ms of service the limited client got at most its
    # seed op; the unlimited client drained
    head = served[:5]
    assert head.count("client.fast") >= 4, served

    # fairness: equal-weight clients interleave
    q2 = MClockQueue(classes={}, client_template=ClassInfo(weight=10.0))
    for i in range(6):
        q2.enqueue("client.a", f"a{i}", now=0.0)
        q2.enqueue("client.b", f"b{i}", now=0.0)
    order = [q2.dequeue(now=0.0)[0] for _ in range(12)]
    for i in range(0, 12, 2):
        assert set(order[i:i + 2]) == {"client.a", "client.b"}, order


def test_client_backlog_backpressure():
    """Client intake is REFUSED at the cap (never blocking the caller —
    it runs on the messenger dispatch thread); sub-op intake always
    flows; refused ops are accepted again once workers drain."""
    import threading
    import time as _t

    from ceph_tpu.osd.op_queue import ShardedOpQueue

    gate = threading.Event()
    done = []

    def handler(klass, item):
        gate.wait(5.0)
        done.append(item)

    wq = ShardedOpQueue(handler, n_shards=1, max_client_backlog=4)
    try:
        accepted = [wq.enqueue("pg", "client", i) for i in range(6)]
        # the first 4 always fit; by the 6th the cap has certainly hit
        # (whether the worker has picked up item 0 yet or not)
        assert accepted[:4] == [True] * 4
        assert False in accepted
        assert wq.enqueue("pg", "client", 99) is False   # still at cap
        # peer traffic flows regardless
        assert wq.enqueue("pg", "subop", "peer") is True
        gate.set()
        deadline = _t.time() + 5
        want = accepted.count(True) + 1   # + the peer op
        while len(done) < want and _t.time() < deadline:
            _t.sleep(0.05)
        assert "peer" in done and 99 not in done
        # drained: client intake resumes
        assert wq.enqueue("pg", "client", 100) is True
        deadline = _t.time() + 5
        while 100 not in done and _t.time() < deadline:
            _t.sleep(0.05)
        assert 100 in done
    finally:
        wq.shutdown()
