"""SLO module — per-tenant burn-rate evaluation over the tenant
device-time ledger and the dmclock accounting feeds.

Objectives are declared per tenant with ``ceph qos slo set`` and ride
mon paxos in the OSDMap's ``slo_db`` (next to ``qos_db``).  Three
objective kinds, any subset per tenant (0 = undeclared):

  * ``reservation_attainment`` — floor on the fraction of the
    tenant's dmclock reservation actually attained: the tenant lane's
    reservation-phase service rate (qos_feed served deltas, summed
    across OSDs) over the qos_db reservation.
  * ``p99_latency_s`` — ceiling on the tenant lane's p99 queue wait,
    computed from windowed DELTAS of the lanes' cumulative wait
    histograms (so the p99 is of the window, not of all time).
  * ``device_share`` — ceiling on the tenant's share of attributed
    device-seconds (tenant_feed deltas over the same window).

Each objective is evaluated as a burn rate normalized so that 1.0
means "exactly at the objective boundary": attainment burns as
``(1 - attained) / (1 - floor)``, the ceilings burn as
``observed / ceiling``.  A tenant is BURNING when both the fast
window (default 5 min) and the slow window (default 1 h) are >= 1.0 —
the classic multi-window rule: the slow window proves the violation
is sustained, the fast window clears the alert promptly once the
pressure stops.  Burning tenants raise the ``QOS_SLO_BURN`` health
warning (via MgrDaemon.health) with per-tenant, per-objective
attribution, and ``slo status`` / ``usage top`` serve the full
picture.

Merging follows the insights-module rule: qos lanes are per-daemon
state and SUM across OSDs, while tenant-usage digests from daemons
sharing one process-global telemetry registry (the in-process
MiniCluster) arrive byte-identical and contribute ONCE, with every
reporter listed — otherwise an N-daemon in-process cluster would
inflate every tenant's device-seconds N-fold.

Attribution and evaluation are measurement-only: nothing here feeds
back into scheduling or batch admission (that is ROADMAP item 1).
"""

from __future__ import annotations

import json
import time
from collections import deque

from ceph_tpu.mgr.module import MgrModule
from ceph_tpu.ops.telemetry import LATENCY_BOUNDS
from ceph_tpu.qos.dmclock import (
    SLO_ATTAINMENT, SLO_DEVICE_SHARE, SLO_P99_LATENCY, slos_from_db)


def _p99_from_bucket_delta(delta: list[float],
                           bounds=LATENCY_BOUNDS) -> float:
    """p99 estimate (upper bucket bound) from a windowed bucket-count
    delta; 0.0 with no samples in the window."""
    total = sum(delta)
    if total <= 0:
        return 0.0
    rank = 0.99 * total
    acc = 0.0
    for i, n in enumerate(delta):
        acc += n
        if acc >= rank:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class Module(MgrModule):
    NAME = "slo"
    COMMANDS = [
        {"prefix": "slo status",
         "help": "per-tenant SLO burn rates over the fast/slow "
                 "windows, with the burning set"},
        {"prefix": "usage top",
         "help": "tenants ranked by attributed device-seconds "
                 "(merged tenant device-time ledger; limit=<n>)"},
    ]
    MODULE_OPTIONS = [
        {"name": "mgr_slo_fast_window_s", "default": 300.0},
        {"name": "mgr_slo_slow_window_s", "default": 3600.0},
        {"name": "mgr_slo_max_samples", "default": 2048},
    ]

    def __init__(self, mgr):
        super().__init__(mgr)
        #: rolling cumulative-counter samples, oldest first
        self._samples: deque = deque()

    # -- feed merging ---------------------------------------------------------

    def _tenant_usage_merged(self) -> dict:
        """Cluster tenant-usage rollup: byte-identical digests (shared
        in-process registry) contribute once; distinct digests sum.
        Returns {tenants: {t: {device_seconds, engines:
        {eng: {ch: row}}, reported_by}}, total_device_seconds,
        reported_by}."""
        try:
            feed = self.get("tenant_feed")
        except Exception:
            feed = {}
        by_digest: dict = {}
        for osd, digest in sorted(feed.items()):
            if not digest:
                continue
            key = json.dumps(digest, sort_keys=True)
            by_digest.setdefault(key, (digest, []))[1].append(osd)
        tenants: dict = {}
        total = 0.0
        reporters: list = []
        for digest, osds in by_digest.values():
            reporters.extend(osds)
            total += float(digest.get("total_device_seconds", 0.0))
            for t, trec in (digest.get("tenants") or {}).items():
                cur = tenants.setdefault(
                    t, {"device_seconds": 0.0, "engines": {},
                        "reported_by": []})
                cur["device_seconds"] += float(
                    trec.get("device_seconds", 0.0))
                cur["reported_by"].extend(osds)
                for eng, chans in (trec.get("engines") or {}).items():
                    dst = cur["engines"].setdefault(eng, {})
                    for ch, row in chans.items():
                        drow = dst.setdefault(
                            ch, {"qos_class": row.get("qos_class", ""),
                                 "device_seconds": 0.0, "batches": 0,
                                 "requests": 0, "stripes": 0,
                                 "wait_p99_s": 0.0})
                        drow["device_seconds"] += float(
                            row.get("device_seconds", 0.0))
                        drow["batches"] += int(row.get("batches", 0))
                        drow["requests"] += int(row.get("requests", 0))
                        drow["stripes"] += int(row.get("stripes", 0))
                        drow["wait_p99_s"] = max(
                            drow["wait_p99_s"],
                            float(row.get("wait_p99_s", 0.0)))
        return {"tenants": tenants, "total_device_seconds": total,
                "reported_by": sorted(set(reporters))}

    def _lanes_merged(self) -> dict:
        """Per-tenant dmclock lane counters summed across OSDs:
        tenant -> {served_res, served_total, backlog, buckets}."""
        try:
            feed = self.get("qos_feed")
        except Exception:
            feed = {}
        out: dict = {}
        for _osd, entry in sorted(feed.items()):
            for lane, row in (entry.get("lanes") or {}).items():
                if not lane.startswith("client."):
                    continue
                tenant = lane.split(".", 1)[1]
                cur = out.setdefault(
                    tenant, {"served_res": 0, "served_total": 0,
                             "backlog": 0,
                             "buckets": [0] * (len(LATENCY_BOUNDS) + 1)})
                served = row.get("served") or {}
                cur["served_res"] += int(served.get("reservation", 0))
                cur["served_total"] += sum(
                    int(v) for v in served.values())
                cur["backlog"] += int(row.get("backlog", 0))
                for i, c in enumerate(row.get("wait_buckets") or ()):
                    if i < len(cur["buckets"]):
                        cur["buckets"][i] += int(c)
        return out

    # -- sampling -------------------------------------------------------------

    def _take_sample(self, now: float) -> dict:
        usage = self._tenant_usage_merged()
        sample = {
            "t": now,
            "total_ds": usage["total_device_seconds"],
            "tenant_ds": {t: rec["device_seconds"]
                          for t, rec in usage["tenants"].items()},
            "lanes": self._lanes_merged(),
        }
        self._samples.append(sample)
        slow = float(self.get_module_option("mgr_slo_slow_window_s",
                                            3600.0))
        cap = int(self.get_module_option("mgr_slo_max_samples", 2048))
        while self._samples and (
                now - self._samples[0]["t"] > slow * 1.2
                or len(self._samples) > cap):
            self._samples.popleft()
        return sample

    def tick(self, now: float) -> None:
        self._take_sample(now)

    def _window_base(self, now: float, window: float) -> dict | None:
        """The retained sample closest to (but not after) now-window;
        the OLDEST sample when history is shorter than the window —
        a young mgr evaluates over what it has rather than nothing."""
        base = None
        for s in self._samples:
            if s["t"] <= now - window:
                base = s
            else:
                break
        if base is None and self._samples:
            base = self._samples[0]
        return base

    # -- burn-rate evaluation -------------------------------------------------

    def _burns(self, latest: dict, base: dict | None) -> dict:
        """Per-tenant {objective: burn} over the delta latest-base for
        every tenant with a declared SLO.  Burn >= 1.0 means the
        objective is violated over this window; vacuous objectives (no
        demand / no samples in the window) burn 0."""
        slos = slos_from_db(self.get_osdmap().slo_db)
        if not slos or base is None or base is latest:
            return {}
        dt = max(1e-9, latest["t"] - base["t"])
        from ceph_tpu.qos.dmclock import profiles_from_db
        profiles = profiles_from_db(self.get_osdmap().qos_db)
        d_total_ds = max(0.0, latest["total_ds"] - base["total_ds"])
        out: dict = {}
        for tenant, slo in slos.items():
            burns: dict = {}
            lane_now = latest["lanes"].get(tenant)
            lane_then = (base["lanes"].get(tenant)
                         or {"served_res": 0, "served_total": 0,
                             "backlog": 0,
                             "buckets": [0] * (len(LATENCY_BOUNDS)
                                               + 1)})
            if slo.reservation_attainment > 0:
                prof = profiles.get(tenant)
                r = prof.reservation if prof is not None else 0.0
                burn = 0.0
                if r > 0 and lane_now is not None:
                    d_total = max(0, lane_now["served_total"]
                                  - lane_then["served_total"])
                    # demand gate: no service AND no backlog in the
                    # window means the floor is vacuously met
                    if d_total > 0 or lane_now["backlog"] > 0:
                        d_res = max(0, lane_now["served_res"]
                                    - lane_then["served_res"])
                        attained = min(1.0, (d_res / dt) / r)
                        floor = slo.reservation_attainment
                        burn = ((1.0 - attained)
                                / max(1e-9, 1.0 - floor))
                burns[SLO_ATTAINMENT] = burn
            if slo.p99_latency_s > 0:
                burn = 0.0
                if lane_now is not None:
                    delta = [max(0, a - b) for a, b in zip(
                        lane_now["buckets"], lane_then["buckets"])]
                    p99 = _p99_from_bucket_delta(delta)
                    if sum(delta) > 0:
                        burn = p99 / slo.p99_latency_s
                burns[SLO_P99_LATENCY] = burn
            if slo.device_share > 0:
                burn = 0.0
                if d_total_ds > 1e-12:
                    d_t = max(0.0, latest["tenant_ds"].get(tenant, 0.0)
                              - base["tenant_ds"].get(tenant, 0.0))
                    share = d_t / d_total_ds
                    burn = share / slo.device_share
                burns[SLO_DEVICE_SHARE] = burn
            out[tenant] = burns
        return out

    def status(self, now: float | None = None) -> dict:
        """The `slo status` payload: per-tenant fast/slow burns and
        the burning set (both windows >= 1.0)."""
        now = time.time() if now is None else now
        fast_w = float(self.get_module_option("mgr_slo_fast_window_s",
                                              300.0))
        slow_w = float(self.get_module_option("mgr_slo_slow_window_s",
                                              3600.0))
        latest = self._samples[-1] if self._samples else None
        if latest is None:
            latest = self._take_sample(now)
        fast = self._burns(latest, self._window_base(latest["t"],
                                                     fast_w))
        slow = self._burns(latest, self._window_base(latest["t"],
                                                     slow_w))
        slos = slos_from_db(self.get_osdmap().slo_db)
        tenants: dict = {}
        for tenant, slo in sorted(slos.items()):
            fb = fast.get(tenant, {})
            sb = slow.get(tenant, {})
            burning = sorted(
                obj for obj in set(fb) | set(sb)
                if fb.get(obj, 0.0) >= 1.0 and sb.get(obj, 0.0) >= 1.0)
            tenants[tenant] = {
                "objectives": slo.to_dict(),
                "burn": {obj: {"fast": round(fb.get(obj, 0.0), 4),
                               "slow": round(sb.get(obj, 0.0), 4)}
                         for obj in sorted(set(fb) | set(sb))},
                "burning": burning,
            }
        return {"windows": {"fast_s": fast_w, "slow_s": slow_w},
                "samples": len(self._samples),
                "tenants": tenants}

    def burn_gauges(self) -> dict:
        """tenant -> {objective: fast burn} for every declared
        objective (the ceph_slo_burn_rate prometheus source)."""
        st = self.status()
        return {t: {obj: rec["burn"][obj]["fast"]
                    for obj in rec["burn"]}
                for t, rec in st["tenants"].items()}

    def health_checks(self) -> list[dict]:
        """QOS_SLO_BURN when any tenant burns on both windows —
        consumed by MgrDaemon.health()."""
        st = self.status()
        burning = {
            t: {obj: rec["burn"][obj] for obj in rec["burning"]}
            for t, rec in st["tenants"].items() if rec["burning"]}
        if not burning:
            return []
        return [{"check": "QOS_SLO_BURN", "severity": "warn",
                 "tenants": burning}]

    def usage_top(self, limit: int = 20) -> dict:
        """Tenants ranked by attributed device-seconds (cumulative,
        cluster-merged), with per-engine/channel splits."""
        usage = self._tenant_usage_merged()
        total = usage["total_device_seconds"]
        rows = []
        for tenant, rec in usage["tenants"].items():
            rows.append({
                "tenant": tenant,
                "device_seconds": round(rec["device_seconds"], 9),
                "share": round(rec["device_seconds"] / total
                               if total else 0.0, 6),
                "engines": rec["engines"],
                "reported_by": sorted(set(rec["reported_by"]))})
        rows.sort(key=lambda r: -r["device_seconds"])
        return {"total_device_seconds": round(total, 9),
                "reported_by": usage["reported_by"],
                "tenants": rows[:limit]}

    # -- command tier ---------------------------------------------------------

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        prefix = cmd.get("prefix", "")
        if prefix == "slo status":
            return json.dumps(self.status()), 0
        if prefix == "usage top":
            limit = int(cmd.get("limit", 20))
            return json.dumps(self.usage_top(limit)), 0
        return f"module {self.NAME} has no command {prefix!r}", -22
