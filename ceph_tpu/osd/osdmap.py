"""OSDMap: the replicated cluster map and its placement pipeline.

Semantics follow src/osd/OSDMap.{h,cc} and src/osd/osd_types.cc:

  object -> pg      ceph_str_hash_rjenkins(object name) -> ps, then
                    ceph_stable_mod(ps, pg_num, pg_num_mask)   (rados.h:85-91)
  pg -> pps         crush_hash32_2(stable_mod(ps, pgp_num, pgp_num_mask), pool)
                    (osd_types.cc:1505-1521 raw_pg_to_pps)
  pps -> raw osds   crush do_rule with per-osd reweight   (OSDMap.cc:2198-2216)
  raw -> up         drop nonexistent/down osds (compact for replicated, NONE
                    holes for erasure)                    (OSDMap.cc:2275-2297)
  upmap             pg_upmap / pg_upmap_items overrides   (OSDMap.cc:2228-2272)
  primary affinity  hash coin-flip primary reselection    (OSDMap.cc:2299+)
  temp              pg_temp / primary_temp                (OSDMap.cc:2417-2445)

The scalar path is the oracle; OSDMapMapping (mapping.py) batches the heavy
middle (pps -> raw osds) on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.crush.hashfn import crush_hash32_2
from ceph_tpu.crush.mapper_ref import crush_do_rule
from ceph_tpu.crush.types import CRUSH_ITEM_NONE, CrushMap

CEPH_NOSD = -1

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

OSD_EXISTS = 1
OSD_UP = 2

MAX_AFFINITY = 0x10000


def _pg_mask(n: int) -> int:
    """calc_pg_masks (osd_types.cc): smallest 2^b-1 >= n-1."""
    if n <= 1:
        return 0
    return (1 << (n - 1).bit_length()) - 1


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """include/rados.h:85-91 — stable under pg_num growth."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def pg_to_pgid(ps: int, pg_num: int) -> int:
    """raw ps -> actual pg id within the pool (raw_pg_to_pg)."""
    return ceph_stable_mod(ps, pg_num, _pg_mask(pg_num))


@dataclass
class PGPool:
    """pg_pool_t (src/osd/osd_types.h) — the subset that affects placement."""

    pool_id: int
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    crush_rule: int = 0
    pg_num: int = 64
    pgp_num: int = 0  # 0 -> pg_num
    # erasure pools carry their code profile (pg_pool_t erasure_code_profile)
    ec_profile: dict = field(default_factory=dict)
    # pool snapshots (pg_pool_t::snaps + snap_seq): snapid -> name
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)
    # cache tiering (pg_pool_t tier fields): a cache pool fronts its
    # tier_of base; the base's read/write_tier redirect the Objecter
    tier_of: int = -1          # set on the CACHE pool
    read_tier: int = -1        # set on the BASE pool (overlay)
    write_tier: int = -1       # set on the BASE pool (overlay)
    cache_mode: str = ""       # "" | "writeback"
    target_max_objects: int = 0
    cache_min_flush_age: float = 0.0
    # per-pool objectstore compression (pg_pool_t compression opts):
    # OSDs push these to their bluestore backend on map apply; ""
    # falls back to the bluestore_compression_* conf
    compression_mode: str = ""        # "" | "none" | "aggressive" | "force"
    compression_algorithm: str = ""   # "" | a compressor plugin name

    def __post_init__(self):
        if self.pgp_num == 0:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return _pg_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _pg_mask(self.pgp_num)

    def raw_pg_to_pps(self, ps: int) -> int:
        """osd_types.cc:1505-1521 — placement seed for CRUSH."""
        return crush_hash32_2(
            ceph_stable_mod(ps, self.pgp_num, self.pgp_num_mask),
            self.pool_id)

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE


@dataclass
class OSDXInfo:
    """osd_xinfo_t (src/osd/osd_types.h): laggy history the monitor uses
    to scale the mark-down grace adaptively.  down_stamp is when the osd
    was last marked down; laggy_probability/laggy_interval are decaying
    averages of how often a marked-down osd turned out to be merely slow
    (it booted again shortly after) and for how long."""

    down_stamp: float = 0.0
    laggy_probability: float = 0.0
    laggy_interval: float = 0.0


@dataclass
class OSDMap:
    """The authoritative cluster map (src/osd/OSDMap.h:class OSDMap)."""

    epoch: int = 1
    crush: CrushMap = field(default_factory=CrushMap)
    max_osd: int = 0
    osd_state: list[int] = field(default_factory=list)   # EXISTS|UP bits
    osd_weight: list[int] = field(default_factory=list)  # 16.16 reweight
    osd_primary_affinity: list[int] = field(default_factory=list)
    osd_addrs: list[str] = field(default_factory=list)   # entity_addr_t
    pools: dict[int, PGPool] = field(default_factory=dict)
    #: central config database (mon/ConfigMonitor.h analog): section
    #: ("global" / "osd" / "osd.3" / "mon" ...) -> {option: value-str};
    #: replicated with the map, applied by daemons via config observers
    config_db: dict = field(default_factory=dict)
    #: auth key table (mon/AuthMonitor analog): entity ("client.admin",
    #: "osd.3", ...) -> base64 key; issued by `auth get-or-create`
    auth_db: dict = field(default_factory=dict)
    #: FSMap (mon/MDSMonitor FSMap analog): {"name", "max_mds",
    #: "metadata_pool", "data_pool", "ranks": {rank-str: {"gid",
    #: "addr"}}, "standbys": [{"gid", "addr"}]} — empty until `fs new`
    fs_db: dict = field(default_factory=dict)
    # overrides
    pg_upmap: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    pg_upmap_items: dict[tuple[int, int], list[tuple[int, int]]] = \
        field(default_factory=dict)
    pg_temp: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    primary_temp: dict[tuple[int, int], int] = field(default_factory=dict)
    #: CRUSH name side-tables (types/items/rules/classes, JSON-shaped —
    #: CrushWrapper type_map/name_map analog), set via `osd setcrushmap`
    crush_names: dict = field(default_factory=dict)
    #: active-mgr record published to every subscriber (MgrMap reduced):
    #: {"active_name": "mgr.0", "addr": "..."} — OSDs stream reports to
    #: it; clients re-target mgr-tier commands at it
    mgr_db: dict = field(default_factory=dict)
    #: monitor membership (MonMap analog): {"epoch": N, "mons":
    #: {rank-str: addr}} — committed through paxos like any map, so
    #: `mon add/rm` reconfigures every quorum member identically and a
    #: probing joiner learns the authoritative member set.  Empty on
    #: clusters bootstrapped with a static monmap before first commit
    mon_db: dict = field(default_factory=dict)
    #: per-tenant QoS profiles (dmclock ClientInfo distribution):
    #: tenant -> {"reservation", "weight", "limit"}, committed by
    #: `ceph qos set/rm` and folded into every OSD's mClock scheduler
    #: on map application — all OSDs agree on the tenant lanes
    qos_db: dict = field(default_factory=dict)
    #: per-tenant SLO objectives: tenant -> {"reservation_attainment",
    #: "p99_latency_s", "device_share"}, committed by `ceph qos slo
    #: set/rm` and consumed by the mgr slo module's burn-rate engine
    #: (measurement-only — no OSD behavior keys off it)
    slo_db: dict = field(default_factory=dict)
    #: per-osd laggy history (osd_xinfo_t vector)
    osd_xinfo: list[OSDXInfo] = field(default_factory=list)

    def copy(self) -> "OSDMap":
        """Cheap structural copy for incremental application: the
        mutable containers are duplicated one level deep; their VALUES
        are never mutated in place by apply_incremental (changed
        entries are replaced wholesale), so sharing them is safe — and
        ~100x cheaper than an encode/decode round trip on a 10k-OSD
        map."""
        import copy as _copy
        m = _copy.copy(self)
        for attr in ("osd_state", "osd_weight", "osd_primary_affinity",
                     "osd_addrs", "osd_xinfo"):
            setattr(m, attr, list(getattr(self, attr)))
        for attr in ("pools", "pg_upmap", "pg_upmap_items", "pg_temp",
                     "primary_temp", "config_db", "auth_db", "fs_db",
                     "crush_names", "mgr_db", "mon_db", "qos_db",
                     "slo_db"):
            setattr(m, attr, dict(getattr(self, attr)))
        return m

    # -- osd state ------------------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        """OSDMap::set_max_osd — grow the state vectors."""
        self.max_osd = n
        for vec, dflt in ((self.osd_state, 0), (self.osd_weight, 0),
                          (self.osd_primary_affinity, MAX_AFFINITY),
                          (self.osd_addrs, "")):
            while len(vec) < n:
                vec.append(dflt)
        while len(self.osd_xinfo) < n:
            self.osd_xinfo.append(OSDXInfo())

    def get_xinfo(self, osd: int) -> OSDXInfo:
        if osd >= len(self.osd_xinfo):
            while len(self.osd_xinfo) < max(self.max_osd, osd + 1):
                self.osd_xinfo.append(OSDXInfo())
        return self.osd_xinfo[osd]

    def is_up(self, osd: int) -> bool:
        return (0 <= osd < self.max_osd
                and bool(self.osd_state[osd] & OSD_UP))

    def exists(self, osd: int) -> bool:
        return (0 <= osd < self.max_osd
                and bool(self.osd_state[osd] & OSD_EXISTS))

    def mark_up(self, osd: int, weight: int = 0x10000) -> None:
        self.osd_state[osd] = OSD_EXISTS | OSD_UP
        self.osd_weight[osd] = weight

    def mark_down(self, osd: int) -> None:
        import time
        self.osd_state[osd] &= ~OSD_UP
        # stamp for the laggy history (OSDMap Incremental down_at /
        # osd_xinfo_t::down_stamp)
        self.get_xinfo(osd).down_stamp = time.time()

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0

    # -- dense operand extraction (fused placement ladder) --------------------

    def dense_osd_vectors(self):
        """(state, weight, affinity) numpy vectors of length
        max(max_osd, 1) — the per-OSD operands of the fused placement
        ladder (ops.placement_kernel).  Sliced to max_osd exactly: the
        scalar pipeline's bounds checks all read ``0 <= o < max_osd``,
        so entries past it must not exist on device either."""
        import numpy as np
        n = max(self.max_osd, 1)
        state = np.zeros(n, dtype=np.int32)
        weight = np.zeros(n, dtype=np.int64)
        affinity = np.full(n, MAX_AFFINITY, dtype=np.int32)
        k = min(self.max_osd, len(self.osd_state))
        state[:k] = self.osd_state[:k]
        k = min(self.max_osd, len(self.osd_weight))
        weight[:k] = self.osd_weight[:k]
        k = min(self.max_osd, len(self.osd_primary_affinity))
        affinity[:k] = self.osd_primary_affinity[:k]
        return state, weight, affinity

    def dense_pool_overrides(self, pool_id: int, pg_num: int,
                             width: int, pairs: int):
        """One pool's sparse overrides as dense per-PG tables for the
        fused ladder: (up_rows, up_len, items, temp_rows, temp_len,
        ptemp).  pg_upmap/pg_temp rows are NONE/NOSD padded to
        ``width``; pg_upmap_items pairs are (-1, -1) padded to
        ``pairs`` (-1 never matches a raw cell, so pads are inert
        while genuine entries — including NONE frms — keep the scalar
        list semantics)."""
        import numpy as np
        up_rows = np.full((pg_num, width), CRUSH_ITEM_NONE,
                          dtype=np.int32)
        up_len = np.zeros(pg_num, dtype=np.int32)
        for (pid, pg), lst in self.pg_upmap.items():
            if pid != pool_id or not (0 <= pg < pg_num):
                continue
            n = min(len(lst), width)
            up_rows[pg, :n] = lst[:n]
            up_len[pg] = n
        items = np.full((pg_num, pairs, 2), -1, dtype=np.int32)
        for (pid, pg), prs in self.pg_upmap_items.items():
            if pid != pool_id or not (0 <= pg < pg_num):
                continue
            for i, (frm, to) in enumerate(prs[:pairs]):
                items[pg, i, 0] = frm
                items[pg, i, 1] = to
        temp_rows = np.full((pg_num, width), CEPH_NOSD, dtype=np.int32)
        temp_len = np.zeros(pg_num, dtype=np.int32)
        for (pid, pg), lst in self.pg_temp.items():
            if pid != pool_id or not (0 <= pg < pg_num):
                continue
            n = min(len(lst), width)
            temp_rows[pg, :n] = lst[:n]
            temp_len[pg] = n
        ptemp = np.full(pg_num, CEPH_NOSD, dtype=np.int32)
        for (pid, pg), osd in self.primary_temp.items():
            if pid == pool_id and 0 <= pg < pg_num:
                ptemp[pg] = osd
        return up_rows, up_len, items, temp_rows, temp_len, ptemp

    # -- placement pipeline (scalar oracle) -----------------------------------

    def _pg_to_raw_osds(self, pool: PGPool, ps: int,
                        pps: int | None = None) -> list[int]:
        """OSDMap.cc:2198-2216."""
        if pps is None:
            pps = pool.raw_pg_to_pps(ps)
        ruleno = pool.crush_rule
        if ruleno < 0 or ruleno >= self.crush.max_rules:
            return []
        return crush_do_rule(self.crush, ruleno, pps, pool.size,
                             self.osd_weight)

    def _apply_upmap(self, pool: PGPool, pgid: tuple[int, int],
                     raw: list[int]) -> list[int]:
        """OSDMap.cc:2228-2272 — explicit overrides, validity-checked."""
        pm = self.pg_upmap.get(pgid)
        if pm:
            if all(self.exists(o) and not self._is_out(o) for o in pm):
                return list(pm)
        pairs = self.pg_upmap_items.get(pgid)
        if pairs:
            raw = list(raw)
            for frm, to in pairs:
                if (frm in raw and to not in raw and self.exists(to)
                        and not self._is_out(to)):
                    raw[raw.index(frm)] = to
        return raw

    def is_out(self, osd: int) -> bool:
        """OSDMap::is_out — weight 0 means CRUSH never places here."""
        return not (0 <= osd < self.max_osd) or self.osd_weight[osd] == 0

    # placement-pipeline internal alias
    _is_out = is_out

    def _raw_to_up_osds(self, pool: PGPool, raw: list[int]
                        ) -> tuple[list[int], int]:
        """OSDMap.cc:2275-2297: erasure keeps positions (NONE holes),
        replicated compacts; primary = first valid."""
        if pool.is_erasure():
            up = [o if (o != CRUSH_ITEM_NONE and self.exists(o)
                        and self.is_up(o)) else CEPH_NOSD for o in raw]
            primary = next((o for o in up if o != CEPH_NOSD), CEPH_NOSD)
        else:
            up = [o for o in raw
                  if o != CRUSH_ITEM_NONE and self.exists(o) and self.is_up(o)]
            primary = up[0] if up else CEPH_NOSD
        return up, primary

    def _apply_primary_affinity(self, seed: int, up: list[int],
                                primary: int) -> int:
        """OSDMap.cc _apply_primary_affinity: the first osd in up that wins
        the affinity coin flip (hash(seed, o) >> 16 < affinity) becomes
        primary; default-affinity osds always win their flip."""
        if not up or all(
                not (0 <= o < self.max_osd)
                or self.osd_primary_affinity[o] == MAX_AFFINITY
                for o in up if o != CEPH_NOSD):
            return primary
        for pos, o in enumerate(up):
            if o == CEPH_NOSD:
                continue
            a = self.osd_primary_affinity[o] \
                if 0 <= o < self.max_osd else MAX_AFFINITY
            if a == MAX_AFFINITY:
                return o
            if (crush_hash32_2(seed, o) >> 16) < a:
                return o
        return primary

    def _finish_pg_mapping(self, pool: PGPool, pgid: tuple[int, int],
                           raw: list[int], pps: int | None = None
                           ) -> tuple[list[int], int, list[int], int]:
        """Post-CRUSH pipeline tail: upmap -> up -> primary affinity -> temps.
        Shared by the scalar path and the batched mapping cache."""
        raw = self._apply_upmap(pool, pgid, raw)
        up, up_primary = self._raw_to_up_osds(pool, raw)
        # affinity seed is pps, not the raw pg id (OSDMap.cc:2410-2447)
        if pps is None:
            pps = pool.raw_pg_to_pps(pgid[1])
        up_primary = self._apply_primary_affinity(pps, up, up_primary)
        acting = list(self.pg_temp.get(pgid, [])) or list(up)
        acting_primary = self.primary_temp.get(pgid, CEPH_NOSD)
        if acting_primary == CEPH_NOSD:
            acting_primary = next(
                (o for o in acting if o != CEPH_NOSD), CEPH_NOSD)
            if acting == up:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def pg_to_up_acting_osds(self, pool_id: int, ps: int
                             ) -> tuple[list[int], int, list[int], int]:
        """OSDMap.cc:2417-2445 — returns (up, up_primary, acting,
        acting_primary)."""
        pool = self.pools[pool_id]
        pgid = (pool_id, pg_to_pgid(ps, pool.pg_num))
        pps = pool.raw_pg_to_pps(pgid[1])
        raw = self._pg_to_raw_osds(pool, pgid[1], pps)
        return self._finish_pg_mapping(pool, pgid, raw, pps)
