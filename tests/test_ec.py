"""Erasure-code plugin framework tests.

Modeled on the reference's gtest suites (src/test/erasure-code/
TestErasureCode{,Jerasure,Isa}.cc): encode/decode round-trips with memcmp
against the original, exhaustive erasure sweeps, minimum_to_decode, chunk
geometry, and plugin-registry failure modes.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import registry_instance
from ceph_tpu.ec.base import SIMD_ALIGN
from ceph_tpu.ec.registry import ErasureCodePluginRegistry

REG = registry_instance()

CONFIGS = [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "7", "m": "3"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "6", "m": "2"}),
    ("jerasure", {"technique": "cauchy_orig", "k": "5", "m": "3"}),
    ("jerasure", {"technique": "cauchy_good", "k": "8", "m": "4"}),
    ("jerasure", {"technique": "blaum_roth", "k": "4", "m": "2", "w": "6"}),
    ("jerasure", {"technique": "liberation", "k": "4", "m": "2", "w": "7"}),
    ("jerasure", {"technique": "liber8tion", "k": "4", "m": "2"}),
    ("isa", {"technique": "reed_sol_van", "k": "4", "m": "2"}),
    ("isa", {"technique": "cauchy", "k": "8", "m": "4"}),
]

IDS = [f"{p}-{prof.get('technique')}-k{prof['k']}m{prof['m']}"
       for p, prof in CONFIGS]


def make(plugin, profile):
    return REG.factory(plugin, dict(profile, runtime="cpu"))


@pytest.mark.parametrize("plugin,profile", CONFIGS, ids=IDS)
def test_encode_decode_roundtrip(plugin, profile):
    codec = make(plugin, profile)
    k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
    encoded = codec.encode(set(range(n)), data)
    assert set(encoded) == set(range(n))
    sizes = {len(v) for v in encoded.values()}
    assert len(sizes) == 1  # all chunks equal size
    # losing any m chunks must still round-trip the payload
    decoded = codec.decode_concat({i: encoded[i] for i in range(k)})
    assert decoded[:len(data)] == data


@pytest.mark.parametrize("plugin,profile", [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2"}),
    ("jerasure", {"technique": "cauchy_good", "k": "4", "m": "3"}),
    ("jerasure", {"technique": "liber8tion", "k": "4", "m": "2"}),
    ("isa", {"technique": "cauchy", "k": "4", "m": "3"}),
], ids=["rs_van", "cauchy_good", "liber8tion", "isa_cauchy"])
def test_exhaustive_erasures(plugin, profile):
    """Every erasure pattern up to m lost chunks decodes bit-identically
    (reference: isa_vandermonde_exhaustive, TestErasureCodeIsa.cc:399)."""
    codec = make(plugin, profile)
    k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
    m = n - k
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 1536, dtype=np.uint8).tobytes()
    encoded = codec.encode(set(range(n)), data)
    want = set(range(k))
    for lost_count in range(1, m + 1):
        for lost in itertools.combinations(range(n), lost_count):
            chunks = {i: encoded[i] for i in range(n) if i not in lost}
            decoded = codec.decode(want, chunks)
            for i in range(k):
                assert decoded[i] == encoded[i], (
                    f"lost={lost}: data chunk {i} mismatch")


def test_minimum_to_decode():
    codec = make("isa", {"k": "4", "m": "2"})
    # all wanted chunks available -> want itself
    assert codec.minimum_to_decode({0, 1}, {0, 1, 2, 3}) == {0, 1}
    # missing chunk -> first k available
    assert codec.minimum_to_decode({0}, {1, 2, 3, 4, 5}) == {1, 2, 3, 4}
    with pytest.raises(IOError):
        codec.minimum_to_decode({0}, {1, 2, 3})


def test_chunk_size_alignment():
    codec = make("isa", {"k": "4", "m": "2"})
    cs = codec.get_chunk_size(1)
    assert cs == SIMD_ALIGN
    assert codec.get_chunk_size(4 * SIMD_ALIGN) == SIMD_ALIGN
    cs = codec.get_chunk_size(10000)
    assert cs * 4 >= 10000 and cs % SIMD_ALIGN == 0


def test_encode_pads_with_zeros():
    codec = make("isa", {"k": "3", "m": "2"})
    data = b"\xff" * 100
    encoded = codec.encode({0, 1, 2}, data)
    joined = b"".join(encoded[i] for i in range(3))
    assert joined[:100] == data
    assert set(joined[100:]) <= {0}  # zero padding (ErasureCode.cc:137-172)


def test_profile_validation_errors():
    with pytest.raises(ValueError):
        make("jerasure", {"technique": "no_such_technique"})
    with pytest.raises(ValueError):
        make("isa", {"k": "abc"})
    with pytest.raises(ValueError):
        make("isa", {"k": "4", "m": "2", "bogus_key": "1"})
    with pytest.raises(ValueError):
        make("isa", {"k": "0", "m": "2"})
    with pytest.raises(KeyError):
        REG.factory("no_such_plugin", {})


def test_registry_is_singleton_with_expected_plugins():
    assert ErasureCodePluginRegistry.instance() is REG
    names = REG.names()
    assert "jerasure" in names and "isa" in names
    with pytest.raises(ValueError):
        REG.add("jerasure", object())  # duplicate registration


def test_isa_vandermonde_guard():
    # m > 4 silently falls back to cauchy (ErasureCodeIsa.cc:330-361)
    codec = make("isa", {"technique": "reed_sol_van", "k": "4", "m": "5"})
    assert codec.technique == "cauchy"
    with pytest.raises(ValueError):
        make("isa", {"technique": "reed_sol_van", "k": "33", "m": "2"})


def test_tpu_and_cpu_runtimes_bit_identical():
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (4, 8, 128), dtype=np.uint8)
    cpu = REG.factory("isa", {"k": "8", "m": "4", "technique": "cauchy",
                              "runtime": "cpu"})
    tpu = REG.factory("isa", {"k": "8", "m": "4", "technique": "cauchy",
                              "runtime": "tpu"})
    np.testing.assert_array_equal(np.asarray(cpu.encode_chunks(data)),
                                  np.asarray(tpu.encode_chunks(data)))


def test_decode_chunks_batched():
    codec = make("jerasure", {"technique": "cauchy_good", "k": "4", "m": "2"})
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (8, 4, 96), dtype=np.uint8)
    parity = np.asarray(codec.encode_chunks(data))
    full = np.concatenate([data, parity], axis=1)
    chosen = [0, 2, 4, 5]  # lost chunks 1 and 3
    rebuilt = np.asarray(codec.decode_chunks(chosen, full[:, chosen], [1, 3]))
    np.testing.assert_array_equal(rebuilt, full[:, [1, 3]])
