"""Sharded op queue with mClock/dmClock QoS scheduling.

The reference pushes every op through a sharded work queue
(osd/OSD.h:1725-1807 ShardedOpWQ over ShardedThreadPool,
common/WorkQueue.h:619): ops shard by PG so one slow PG cannot head-of-line
block the rest, and within a shard an mClock scheduler (osd/mClock*,
dmclock submodule) arbitrates between op classes — client I/O, sub-ops,
recovery, scrub, snap-trim — by (reservation, weight, limit) tags.

This is that engine, reduced to its algorithmic core:

  * `ShardedOpQueue(n_shards, n_workers_per_shard)` — items enqueue by a
    shard key (the pgid), each shard owns an `MClockQueue` + worker
    thread(s); per-(shard, class) FIFO order is preserved, which with
    pg-keyed sharding gives the per-PG ordering the OSD requires.
  * `MClockQueue` — dmclock tag math: each class k has a reservation
    r_k (ops/s guaranteed), weight w_k (share of excess), limit l_k
    (ops/s cap, 0 = none).  Tags track the class's HEAD item and advance
    per served op by that op's distributed-service increments
        R_k = max(now, R_k_prev + rho/r_k)
        L_k = max(now, L_k_prev + delta/l_k)
        P_k = max(now, P_k_prev + delta/w_k)     (proportional tag)
    where (delta, rho) ride each op from the client's ServiceTracker
    (ceph_tpu.qos.dmclock): delta counts the tenant's completions on
    ANY osd since its last op here, rho the reservation-phase subset —
    so reservations and limits hold for the tenant cluster-wide.  Local
    ops and old peers carry delta = rho = 1, which is exactly mClock.
    Dequeue picks the earliest R-tag that is ≤ now (reservation phase);
    otherwise the earliest P-tag among classes whose L-tag permits
    (weight phase); otherwise — every backlogged class limit-throttled —
    the earliest L-tag (work-conserving fallback: serve whoever's cap
    expires soonest rather than idle).  Every dequeue reports the phase
    served and the op's queue wait, feeding the reply's phase echo (rho
    accounting), the qos_wait trace event, and ``dump_qos_stats``.

dmclock reference: the mClock paper's tag rules as embodied in the
reference's `osd_op_queue=mclock_*` options (common/options.cc), plus
the dmClock (delta, rho) extension from src/dmclock.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ceph_tpu.ops.telemetry import LATENCY_BOUNDS, Histogram
from ceph_tpu.qos.dmclock import (
    PHASE_LIMIT, PHASE_RESERVATION, PHASE_WEIGHT)


@dataclass
class ClassInfo:
    """QoS parameters for one op class (dmclock ClientInfo analog)."""

    reservation: float = 0.0   # guaranteed ops/s (0 = none)
    weight: float = 1.0        # share of excess capacity
    limit: float = 0.0         # ops/s cap (0 = unlimited)


#: default op classes (osd_op_queue mclock profiles: client ops get
#: weight-dominant service, recovery/scrub/snaptrim run in the excess;
#: deep-scrub chunks and replica scrub-map ops ride the dedicated
#: background_best_effort class — the reference's mClockScheduler
#: class of the same name — whose weight/limit the daemon wires to
#: osd_scrub_background_weight/_limit)
DEFAULT_CLASSES = {
    "client": ClassInfo(reservation=0.0, weight=100.0, limit=0.0),
    "subop": ClassInfo(reservation=0.0, weight=80.0, limit=0.0),
    "recovery": ClassInfo(reservation=10.0, weight=10.0, limit=0.0),
    "scrub": ClassInfo(reservation=0.0, weight=5.0, limit=100.0),
    "snaptrim": ClassInfo(reservation=0.0, weight=5.0, limit=100.0),
    "background_best_effort": ClassInfo(reservation=0.0, weight=1.0,
                                        limit=0.0),
}

_PHASES = (PHASE_RESERVATION, PHASE_WEIGHT, PHASE_LIMIT)


@dataclass
class _ClassState:
    info: ClassInfo
    #: queued (item, delta, rho, t_enq, r_tag, p_tag, l_tag): each
    #: request carries ITS OWN tags, assigned at arrival by chaining
    #: from the previous request's (dmclock RequestTag — the chain is
    #: what makes overloaded reservations share r-proportionally
    #: instead of round-robin); the scheduler reads the head's tags
    q: deque = field(default_factory=deque)
    #: chain tail: the tags of the most recently enqueued request
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0
    #: class created on demand (per-client / per-tenant lane) — subject
    #: to idle eviction, unlike the static class table
    dynamic: bool = False
    last_active: float = 0.0
    # -- dump_qos_stats accounting (per class, merged across shards) --
    served: list = field(default_factory=lambda: [0, 0, 0, 0])
    wait_sum: float = 0.0
    wait_max: float = 0.0
    enqueued: int = 0
    #: queue-wait distribution (the mgr slo module's p99 source: the
    #: digest ships cumulative buckets, and windowed bucket DELTAS give
    #: an exact rolling p99 estimate without per-op samples)
    wait_hist: Histogram = field(
        default_factory=lambda: Histogram(LATENCY_BOUNDS))


class MClockQueue:
    """Single-shard dmClock scheduler over named op classes.

    Client ops may be tagged per client or per TENANT ("client.<id>" /
    "client.<tenant>" class names, mClockClientQueue analog): each lane
    gets its own dmclock tag stream — from ``client_profiles`` when the
    OSDMap's qos_db names the tenant (``ceph qos set``), else from the
    ``client_template`` — so one chatty tenant cannot starve the rest.
    Idle dynamic lanes are evicted after ``idle_timeout`` seconds of
    quiet so millions of one-shot clients never grow the table without
    bound; their served/wait totals fold into an ``evicted`` rollup so
    dump_qos_stats stays truthful across evictions.
    """

    #: default quiet period before an idle dynamic lane is dropped
    #: (osd_qos_idle_client_timeout overrides per daemon)
    CLIENT_IDLE_PRUNE = 60.0

    #: eviction sweep cadence, in dynamic-lane enqueues
    _PRUNE_EVERY = 256

    def __init__(self, classes: dict[str, ClassInfo] | None = None,
                 client_template: ClassInfo | None = None,
                 client_profiles: dict[str, ClassInfo] | None = None,
                 idle_timeout: float | None = None):
        self._classes: dict[str, _ClassState] = {}
        for name, info in (classes or DEFAULT_CLASSES).items():
            self._classes[name] = _ClassState(info=info)
        self.client_template = client_template
        #: full-class-name ("client.<tenant>") -> ClassInfo from the
        #: distributed qos_db; consulted before the template
        self.client_profiles = dict(client_profiles or {})
        self.idle_timeout = (self.CLIENT_IDLE_PRUNE if idle_timeout is None
                             else float(idle_timeout))
        #: first-segment group -> queued items (O(1) class_backlog for
        #: the hot dot-free prefixes: "client" covers client + client.*)
        self._group_len: dict[str, int] = {}
        self._enq_count = 0
        self._len = 0
        #: rollup of evicted lanes (bounded: totals only)
        self._evicted = {"classes": 0, "served": [0, 0, 0, 0],
                         "wait_sum": 0.0, "enqueued": 0,
                         "wait_hist": Histogram(LATENCY_BOUNDS)}

    def __len__(self) -> int:
        return self._len

    @staticmethod
    def _group(name: str) -> str:
        return name.split(".", 1)[0]

    def exact_backlog(self, klass: str) -> int:
        """Queued items of exactly this class — O(1), the per-lane
        intake-cap check on the enqueue hot path."""
        st = self._classes.get(klass)
        return len(st.q) if st is not None else 0

    def class_backlog(self, prefix: str) -> int:
        """Queued items across classes matching the prefix (the class
        itself or prefix.* descendants).  Dot-free prefixes — the hot
        aggregate check ("client") — read a maintained per-group
        counter instead of scanning every lane."""
        if "." not in prefix:
            return self._group_len.get(prefix, 0)
        dotted = prefix + "."
        return sum(len(st.q) for n, st in self._classes.items()
                   if n == prefix or n.startswith(dotted))

    def _client_info(self, klass: str) -> ClassInfo:
        prof = self.client_profiles.get(klass)
        if prof is not None:
            return ClassInfo(reservation=prof.reservation,
                             weight=prof.weight, limit=prof.limit)
        if klass.startswith("client.") and self.client_template:
            t = self.client_template
            return ClassInfo(reservation=t.reservation, weight=t.weight,
                             limit=t.limit)
        return ClassInfo()

    def set_client_profiles(
            self, profiles: dict[str, ClassInfo]) -> None:
        """Fold a new qos_db snapshot in: future lanes resolve against
        it, and EXISTING dynamic lanes re-resolve now — a `ceph qos
        set` takes effect on a backlogged tenant without waiting for
        its queue to drain."""
        self.client_profiles = dict(profiles)
        for name, st in self._classes.items():
            if st.dynamic:
                info = self._client_info(name)
                if (info.reservation, info.weight, info.limit) != (
                        st.info.reservation, st.info.weight,
                        st.info.limit):
                    st.info = info
                    self._retag(st)

    @staticmethod
    def _tag_chain(st: _ClassState, now: float, delta: int,
                   rho: int) -> tuple[float, float, float]:
        """Tags for the next request of the class (dmclock RequestTag):
        an idle class restarts its chain from arrival (no accumulated
        debt OR credit); a backlogged class chains max(prev + inc,
        arrival), per-op increments scaled by the request's distributed
        (delta, rho).  Weight 0 is treated as the minimum share, not a
        crash."""
        i = st.info
        if not st.q:
            r = now + (rho / i.reservation if i.reservation else 0.0)
            p = now + delta / max(i.weight, 1e-6)
            lt = now + (delta / i.limit if i.limit else 0.0)
        else:
            r = (max(st.r_tag + rho / i.reservation, now)
                 if i.reservation else 0.0)
            p = max(st.p_tag + delta / max(i.weight, 1e-6), now)
            lt = (max(st.l_tag + delta / i.limit, now)
                  if i.limit else 0.0)
        return r, p, lt

    def enqueue(self, klass: str, item, now: float | None = None,
                delta: int = 1, rho: int = 1) -> None:
        now = time.monotonic() if now is None else now
        delta = max(1, int(delta))
        rho = max(0, int(rho))
        st = self._classes.get(klass)
        if st is None:
            st = self._classes[klass] = _ClassState(
                info=self._client_info(klass), dynamic=True)
        if st.dynamic:
            st.last_active = now
            self._enq_count += 1
            if self._enq_count % self._PRUNE_EVERY == 0:
                self.prune(now)
        r, p, lt = self._tag_chain(st, now, delta, rho)
        st.r_tag, st.p_tag, st.l_tag = r, p, lt
        st.q.append((item, delta, rho, now, r, p, lt))
        st.enqueued += 1
        self._len += 1
        g = self._group(klass)
        self._group_len[g] = self._group_len.get(g, 0) + 1

    def prune(self, now: float | None = None) -> None:
        """Evict idle dynamic lanes (quiet for idle_timeout with an
        empty queue), folding their accounting into the rollup."""
        now = time.monotonic() if now is None else now
        stale = [n for n, st in self._classes.items()
                 if st.dynamic and not st.q
                 and now - st.last_active > self.idle_timeout]
        ev = self._evicted
        for n in stale:
            st = self._classes.pop(n)
            ev["classes"] += 1
            ev["enqueued"] += st.enqueued
            ev["wait_sum"] += st.wait_sum
            for p in range(4):
                ev["served"][p] += st.served[p]
            evh = ev["wait_hist"]
            for i, c in enumerate(st.wait_hist.buckets):
                evh.buckets[i] += c
            evh.sum += st.wait_hist.sum

    def _retag(self, st: _ClassState) -> None:
        """Rebuild the class's tag chain under a CHANGED profile
        (`ceph qos set` on a backlogged tenant): every queued request
        re-tags from its recorded arrival and (delta, rho), so the new
        reservation/weight/limit govern the existing backlog too —
        not just ops enqueued after the map landed."""
        old = st.q
        st.q = deque()
        for item, delta, rho, t_enq, _r, _p, _l in old:
            r, p, lt = self._tag_chain(st, t_enq, delta, rho)
            st.r_tag, st.p_tag, st.l_tag = r, p, lt
            st.q.append((item, delta, rho, t_enq, r, p, lt))

    def _pop(self, name: str, st: _ClassState, now: float,
             phase: int) -> tuple:
        item, _delta, _rho, t_enq, _r, _p, _l = st.q.popleft()
        self._len -= 1
        g = self._group(name)
        left = self._group_len.get(g, 1) - 1
        if left:
            self._group_len[g] = left
        else:
            self._group_len.pop(g, None)
        wait = max(0.0, now - t_enq)
        st.served[phase] += 1
        st.wait_sum += wait
        st.wait_hist.add(wait)
        if wait > st.wait_max:
            st.wait_max = wait
        if st.dynamic:
            st.last_active = now
        return name, item, phase, wait

    def dequeue(self, now: float | None = None):
        """Return (class, item, phase, wait_seconds) or None if empty.
        Selection reads each class's HEAD request tags (q[0][4:7])."""
        now = time.monotonic() if now is None else now
        backlogged = [(n, st) for n, st in self._classes.items() if st.q]
        if not backlogged:
            return None
        # phase 1: honor reservations that are due
        due = [(st.q[0][4], n, st) for n, st in backlogged
               if st.info.reservation and st.q[0][4] <= now]
        if due:
            _tag, name, st = min(due)
            return self._pop(name, st, now, PHASE_RESERVATION)
        # phase 2: weight-proportional among classes under their limit
        ok = [(st.q[0][5], n, st) for n, st in backlogged
              if not st.info.limit or st.q[0][6] <= now]
        if ok:
            _tag, name, st = min(ok)
            return self._pop(name, st, now, PHASE_WEIGHT)
        # phase 3: everything limited — work-conserving: earliest limit tag
        _tag, name, st = min((st.q[0][6], n, st) for n, st in backlogged)
        return self._pop(name, st, now, PHASE_LIMIT)

    def dump_qos(self) -> dict:
        """Per-class accounting snapshot (dump_qos_stats feed)."""
        classes = {}
        for n, st in self._classes.items():
            classes[n] = {
                "backlog": len(st.q),
                "enqueued": st.enqueued,
                "served": {"reservation": st.served[PHASE_RESERVATION],
                           "weight": st.served[PHASE_WEIGHT],
                           "limit": st.served[PHASE_LIMIT]},
                "wait_sum_s": st.wait_sum,
                "wait_max_s": st.wait_max,
                "wait_buckets": list(st.wait_hist.buckets),
                "dynamic": st.dynamic,
                "profile": {"reservation": st.info.reservation,
                            "weight": st.info.weight,
                            "limit": st.info.limit}}
        ev = self._evicted
        return {"classes": classes,
                "evicted": {
                    "classes": ev["classes"],
                    "enqueued": ev["enqueued"],
                    "wait_sum_s": ev["wait_sum"],
                    "served": {
                        "reservation": ev["served"][PHASE_RESERVATION],
                        "weight": ev["served"][PHASE_WEIGHT],
                        "limit": ev["served"][PHASE_LIMIT]}}}


class ShardedOpQueue:
    """N independent dmClock shards, each drained by worker thread(s).

    Items shard by key (hash(pgid) % n_shards) so per-PG order is kept
    and one stuck PG only wedges its shard (ShardedOpWQ semantics).

    The handler may take a third parameter — ``handler(klass, item,
    served)`` with ``served = (phase, wait_seconds)`` — to learn which
    dmclock phase served the op and how long it queued (the MOSDOpReply
    phase echo + qos_wait trace event); two-parameter handlers keep
    working unchanged.
    """

    #: tagged clients together may queue up to this many times the
    #: per-client cap before the shard refuses all client intake
    CLIENT_AGGREGATE_FACTOR = 16

    def __init__(self, handler, n_shards: int = 2,
                 n_workers_per_shard: int = 1,
                 classes: dict[str, ClassInfo] | None = None,
                 name: str = "osd",
                 client_template: ClassInfo | None = None,
                 max_client_backlog: int = 0,
                 client_profiles: dict[str, ClassInfo] | None = None,
                 idle_timeout: float | None = None):
        self._handler = handler
        try:
            params = inspect.signature(handler).parameters.values()
            # count what can actually be fed POSITIONALLY (keyword-only
            # and **kwargs can't take the served tuple; counting them
            # would make the worker call a 2-positional handler with 3
            # args and wedge the queue); *args handlers take
            # everything, and an unsignaturable callable is assumed
            # modern (3-arg) rather than silently losing phase data
            positional = sum(
                1 for p in params
                if p.kind in (p.POSITIONAL_ONLY,
                              p.POSITIONAL_OR_KEYWORD))
            self._handler_takes_served = (
                positional >= 3
                or any(p.kind == p.VAR_POSITIONAL for p in params))
        except (TypeError, ValueError):
            self._handler_takes_served = True
        self._n = max(1, n_shards)
        self._shards = []
        self._stop = False
        #: client-intake cap per shard (0 = unbounded): enqueue of a
        #: "client" / "client.N" op BLOCKS while the shard's client
        #: backlog is at the cap — dispatch-side backpressure, while
        #: peer/recovery classes always flow (the reference gates client
        #: intake with throttles end-to-end; sub-ops must not deadlock)
        self.max_client_backlog = max_client_backlog
        self._threads: list[threading.Thread] = []
        for s in range(self._n):
            q = MClockQueue(classes, client_template=client_template,
                            client_profiles=client_profiles,
                            idle_timeout=idle_timeout)
            # analysis: allow[bare-lock] -- per-shard parking condition: waiters hold no other lock; one node per shard would still merge by name
            cv = threading.Condition()
            self._shards.append((q, cv))
            for w in range(max(1, n_workers_per_shard)):
                t = threading.Thread(
                    target=self._worker, args=(q, cv),
                    name=f"{name}-opwq-{s}.{w}", daemon=True)
                t.start()
                self._threads.append(t)

    def enqueue(self, shard_key, klass: str, item,
                delta: int = 1, rho: int = 1) -> bool:
        """Queue an item; returns False when a CLIENT op is refused at
        the per-shard backlog cap.  Refusal (not blocking) is the
        backpressure mechanism: the caller runs on the daemon's single
        messenger dispatch thread, and blocking it on one wedged shard
        would gate heartbeats, sub-ops and map updates for every healthy
        PG.  A refused client op gets no reply; the client's timeout
        resend retries it (and dedups against the log if it already
        landed) — the reference's front-door throttles achieve the same
        per-client pushback via per-connection reader blocking, which a
        shared dispatch thread cannot afford."""
        q, cv = self._shards[hash(shard_key) % self._n]
        with cv:
            if (self.max_client_backlog
                    and (klass == "client" or klass.startswith("client."))):
                # with per-client tagging the cap is PER CLIENT class:
                # one chatty client hitting its cap must not refuse every
                # other client's intake (that would re-create exactly the
                # head-of-line blocking the per-client dmclock tags
                # remove); untagged "client" ops keep the aggregate cap.
                # A larger aggregate ceiling still bounds total shard
                # memory — without it N distinct client ids could queue
                # N x cap items between them
                if (klass.startswith("client.")
                        and q.exact_backlog(klass)
                        >= self.max_client_backlog):
                    return False
                total_cap = (self.max_client_backlog
                             if klass == "client"
                             else self.max_client_backlog
                             * self.CLIENT_AGGREGATE_FACTOR)
                if q.class_backlog("client") >= total_cap:
                    return False
            q.enqueue(klass, item, delta=delta, rho=rho)
            cv.notify()
        return True

    def set_client_profiles(
            self, profiles: dict[str, ClassInfo]) -> None:
        """Push a new qos_db snapshot into every shard (map change)."""
        for q, cv in self._shards:
            with cv:
                q.set_client_profiles(profiles)

    def set_idle_timeout(self, timeout: float) -> None:
        """Hot-reload the idle-lane eviction quiet period."""
        for q, cv in self._shards:
            with cv:
                q.idle_timeout = float(timeout)

    def dump_qos(self) -> dict:
        """dump_qos_stats payload: the per-class accounting merged
        across shards (served counts sum, wait_max maxes)."""
        merged: dict = {}
        evicted = {"classes": 0, "enqueued": 0, "wait_sum_s": 0.0,
                   "served": {"reservation": 0, "weight": 0, "limit": 0}}
        for q, cv in self._shards:
            with cv:
                d = q.dump_qos()
            for name, row in d["classes"].items():
                agg = merged.get(name)
                if agg is None:
                    merged[name] = dict(row)
                    merged[name]["served"] = dict(row["served"])
                    continue
                agg["backlog"] += row["backlog"]
                agg["enqueued"] += row["enqueued"]
                agg["wait_sum_s"] += row["wait_sum_s"]
                agg["wait_max_s"] = max(agg["wait_max_s"],
                                        row["wait_max_s"])
                for i, c in enumerate(row["wait_buckets"]):
                    agg["wait_buckets"][i] += c
                for ph, n in row["served"].items():
                    agg["served"][ph] += n
                agg["profile"] = row["profile"]
            ev = d["evicted"]
            evicted["classes"] += ev["classes"]
            evicted["enqueued"] += ev["enqueued"]
            evicted["wait_sum_s"] += ev["wait_sum_s"]
            for ph, n in ev["served"].items():
                evicted["served"][ph] += n
        return {"shards": self._n, "classes": merged, "evicted": evicted}

    def shutdown(self) -> None:
        self._stop = True
        for _q, cv in self._shards:
            with cv:
                cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)

    def _worker(self, q: MClockQueue, cv: threading.Condition) -> None:
        while True:
            with cv:
                while not self._stop and len(q) == 0:
                    cv.wait(timeout=0.1)
                if self._stop:
                    return
                got = q.dequeue()
            if got is None:
                continue
            klass, item, phase, wait = got
            try:
                if self._handler_takes_served:
                    self._handler(klass, item, (phase, wait))
                else:
                    self._handler(klass, item)
            except Exception:
                from ceph_tpu.common.logging import get_logger
                get_logger("osd").exception("opwq handler failed (%s)",
                                            klass)
