"""Jit-boundary purity lint (check family ``jit-purity``).

Functions traced by ``jax.jit`` — and the batch closures handed to
the dispatch engines — execute under tracing/retracing rules that make
host side effects hazards:

* ``time.*`` / ``random.*`` / ``np.random``: traced once, frozen into
  the compiled executable — silently wrong on every cache hit;
* ``conf.get``: a hot-reloadable option read mid-trace splits one
  logical batch across two config states (the pow-2 bucketing
  discipline assumes the batch is uniform);
* logging (``dout``/``logger``/``print``): fires at trace time, not
  call time, and on the dispatch thread stalls the pipeline;
* mutating captured state (``self.x = ..``, ``global``/``nonlocal``
  writes, subscript stores to captured names): tracer leaks and
  retrace-order dependence.

Targets: functions decorated with ``jax.jit`` (bare or via
``functools.partial``), named functions passed to ``jax.jit(..)``
calls, and functions/closures passed as the ``fn`` argument of the
engines' ``submit``/``submit_chunks``/``submit_decode_chunks``.  The
scan covers the target's own body and its locally nested defs — the
host-side wrappers *around* a jit call (telemetry timing etc.) are
exactly the code that SHOULD do host work, so the scan does not chase
cross-module calls.

Placement scaffolding exemption: the mesh-sharded dispatch path
(ops.dispatch) hands engine closures SHARDED batches, and those
closures legitimately build/cache device placements host-side —
``jax.device_put``, ``NamedSharding``/``PartitionSpec`` construction,
``make_mesh`` — before invoking the jitted kernel.  These run on the
engine thread OUTSIDE any trace (the closure CALLS jit; it is not
traced itself), so a captured-state store whose value is placement
construction is host-side scaffolding, not a tracer leak: the
mutation check skips stores whose right-hand side IS a call to one of
``_PLACEMENT_FNS`` (the whole value — a compound RHS stays flagged).
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

_SUBMIT_METHODS = {"submit", "submit_chunks", "submit_decode_chunks",
                   "submit_flat_firstn", "submit_do_rule"}

#: host-side device-placement constructors: a store whose value is
#: built from one of these is sharding scaffolding (see module
#: docstring), exempt from the captured-state mutation check
_PLACEMENT_FNS = {"device_put", "NamedSharding", "PartitionSpec",
                  "make_mesh"}


def _is_placement_value(value) -> bool:
    """True when an assignment's RHS IS a placement-scaffolding call
    (``jax.device_put(..)`` / ``NamedSharding(..)`` / ...) — the whole
    value, not merely containing one: a compound RHS like
    ``(traced_x, jax.device_put(..))`` could smuggle tracer-derived
    state into captured storage behind an incidental placement call,
    so it stays a mutation finding."""
    return (isinstance(value, ast.Call)
            and bool(ch := name_chain(value.func))
            and ch[-1] in _PLACEMENT_FNS)


def _is_jit_expr(node) -> bool:
    """``jax.jit`` / ``jit`` / ``functools.partial(jax.jit, ..)``."""
    chain = name_chain(node)
    if chain and chain[-1] == "jit":
        return True
    if isinstance(node, ast.Call):
        ch = name_chain(node.func)
        if ch and ch[-1] == "partial" and node.args:
            a0 = name_chain(node.args[0])
            return bool(a0) and a0[-1] == "jit"
    return False


def _targets(index: TreeIndex):
    """(FunctionInfo, why) for every jit-traced / engine-submitted
    function we can resolve statically."""
    out = []
    seen = set()

    def add(fn, why):
        if fn is not None and fn not in seen:
            seen.add(fn)
            out.append((fn, why))

    for fi in index.all_functions():
        for dec in fi.decorators:
            if _is_jit_expr(dec):
                add(fi, "decorated with jax.jit")
    for fi in index.all_functions():
        for cs in fi.call_sites:
            node = cs.node
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain:
                continue
            if chain[-1] == "jit" and node.args:
                a0 = name_chain(node.args[0])
                if a0 and len(a0) == 1:
                    add(index.resolve_call(fi, ("name", a0[0])),
                        "passed to jax.jit")
            elif chain[-1] in _SUBMIT_METHODS:
                # engine.submit(key, fn, data, ..): fn is arg 1 for
                # submit, arg 0 shape varies for the helpers — resolve
                # any bare-name argument that names a local function
                for arg in node.args:
                    a = name_chain(arg)
                    if a and len(a) == 1:
                        g = index.resolve_call(fi, ("name", a[0]))
                        if g is not None and (g.parent is not None
                                              or a[0] == "fn"):
                            add(g, f"submitted to the dispatch engine "
                                   f"via {chain[-1]}")
    return out


def _param_names(fn) -> set:
    node = fn.node
    names: set = set()
    args = getattr(node, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
    return names


def _bound_names(target, names: set) -> None:
    """Names BOUND by an assignment target.  A Subscript/Attribute
    store (``state["n"] = ..``) binds nothing — its base stays a
    captured name, which is exactly what the mutation check flags."""
    if isinstance(target, ast.Name):
        names.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            _bound_names(e, names)
    elif isinstance(target, ast.Starred):
        _bound_names(target.value, names)


def _local_names(fn) -> set:
    """Locally-bound names (assignment/loop/with targets)."""
    node = fn.node
    names: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                _bound_names(t, names)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            _bound_names(n.target, names)
        elif isinstance(n, (ast.For, ast.comprehension)):
            _bound_names(n.target, names)
        elif isinstance(n, ast.withitem) and n.optional_vars:
            _bound_names(n.optional_vars, names)
    return names


def _scan(fn, why, findings) -> None:
    params = _param_names(fn)
    local = _local_names(fn) - params
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            chain = name_chain(node.func)
            if not chain:
                continue
            dotted = ".".join(chain)
            if chain[0] == "time" and len(chain) > 1:
                _emit(findings, fn, node.lineno, "clock",
                      f"{dotted}() reads the host clock", why)
            elif (chain[0] in ("random",) or chain[:2] ==
                  ("np", "random") or chain[:2] == ("numpy", "random")):
                _emit(findings, fn, node.lineno, "random",
                      f"{dotted}() draws host randomness", why)
            elif len(chain) >= 2 and chain[-2] == "conf" and \
                    chain[-1] == "get":
                _emit(findings, fn, node.lineno, "conf",
                      f"{dotted}() reads hot-reloadable config", why)
            elif chain[-1] == "dout" or chain[0] in ("logging",) or \
                    chain[0] == "print" or (len(chain) == 2 and
                                            chain[0] == "logger"):
                _emit(findings, fn, node.lineno, "logging",
                      f"{dotted}() logs at trace time", why)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            _emit(findings, fn, node.lineno, "mutation",
                  f"{'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                  f" write to captured state", why)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            # sharding-scaffolding stores are exempt ONLY in engine
            # submit closures (they run on the engine thread outside
            # any trace); inside a genuinely jit-TRACED function the
            # same store executes once at trace time and never again —
            # exactly the staleness hazard this check exists to catch
            if (isinstance(node, ast.Assign)
                    and why.startswith("submitted")
                    and _is_placement_value(node.value)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    base = t.value
                    while isinstance(base, (ast.Attribute,
                                            ast.Subscript)):
                        base = base.value
                    # stores into params (incl. self) or captured
                    # names mutate state the trace cache can't see;
                    # stores into locally-created containers are
                    # trace-time scaffolding and fine
                    if isinstance(base, ast.Name) and (
                            base.id in params or base.id not in local):
                        _emit(findings, fn, node.lineno, "mutation",
                              f"store into captured object "
                              f"{base.id!r}", why)


def _emit(findings, fn, line, code, detail, why):
    findings.append(Finding(
        "jit-purity", fn.module.relpath, line, code,
        f"{detail} inside {fn.qualname} ({why}) — retrace/correctness "
        f"hazard in traced code"))


def check(index: TreeIndex):
    findings: list = []
    for fn, why in _targets(index):
        _scan(fn, why, findings)
    # dedupe (a function can be both decorated and passed around)
    out, seen = [], set()
    for f in findings:
        k = (f.path, f.line, f.code)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
