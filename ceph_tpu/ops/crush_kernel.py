"""Batched CRUSH placement kernels (JAX).

The reference evaluates placement one x at a time (``crush_do_rule``,
src/crush/mapper.c:900) and parallelises bulk remaps with a thread pool
(``ParallelPGMapper``, src/osd/OSDMapMapping.h:17).  Here the same math is one
device call batched over x: every PG's straw2 draws for a bucket are a (N, size)
tensor, the winner an argmax, and the firstn collision/retry ladder a masked
``lax.while_loop`` — no data-dependent Python control flow, static shapes, so XLA
tiles the whole remap onto the VPU.

Bit-exactness contract: every function here matches the scalar oracle in
ceph_tpu.crush.mapper_ref (itself written against src/crush/mapper.c semantics)
exactly, including the 16.16 fixed-point straw2 draw (``crush_ln`` fixed-point
tables, u64 wrap-around product, truncating s64 division) and the first-max-wins
tie-break of ``bucket_straw2_choose`` (mapper.c:361-384).

int64 is required (jax_enable_x64 is switched on in ceph_tpu.__init__): straw2
draws are s64 and the ln tables are 48-bit fixed point.

Mesh contract: every kernel here is elementwise along the x (batch) axis —
each lane's draws, retry ladder and reject tests read only that lane plus the
replicated map operands — so a mesh-sharded dispatch engine may split x over
any device mesh with bit-identical results (GSPMD partitions the jitted call;
``jnp.any`` in the while_loop conds becomes the only cross-shard collective).
Callers placing x with a committed sharding must hand the operand tables in
uncommitted (numpy/jnp.asarray) or replicated over the SAME mesh — the submit
helpers in ops.dispatch do the latter when they see a sharded batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.crush.hashfn import CRUSH_HASH_SEED
from ceph_tpu.crush.ln_table import lh_table, ll_table, rh_table
from ceph_tpu.crush.types import S64_MIN

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# rjenkins1 hash family (crush/hash.c semantics, elementwise on uint32 arrays)
# ---------------------------------------------------------------------------

def _mix(a, b, c):
    a = a - b - c; a = a ^ (c >> 13)
    b = b - c - a; b = b ^ (a << 8)
    c = c - a - b; c = c ^ (b >> 13)
    a = a - b - c; a = a ^ (c >> 12)
    b = b - c - a; b = b ^ (a << 16)
    c = c - a - b; c = c ^ (b >> 5)
    a = a - b - c; a = a ^ (c >> 3)
    b = b - c - a; b = b ^ (a << 10)
    c = c - a - b; c = c ^ (b >> 15)
    return a, b, c


def _const(shape_like, v):
    return jnp.full(jnp.shape(shape_like), v, dtype=_U32)


def hash32_2(a, b):
    """crush_hash32_2 (hash.c:38-50), elementwise over broadcast uint32 arrays."""
    a = jnp.asarray(a).astype(_U32)
    b = jnp.asarray(b).astype(_U32)
    a, b = jnp.broadcast_arrays(a, b)
    h = jnp.uint32(CRUSH_HASH_SEED) ^ a ^ b
    x = _const(h, 231232)
    y = _const(h, 1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3(a, b, c):
    """crush_hash32_3 (hash.c:52-66), elementwise over broadcast uint32 arrays."""
    a = jnp.asarray(a).astype(_U32)
    b = jnp.asarray(b).astype(_U32)
    c = jnp.asarray(c).astype(_U32)
    a, b, c = jnp.broadcast_arrays(a, b, c)
    h = jnp.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = _const(h, 231232)
    y = _const(h, 1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_4(a, b, c, d):
    """crush_hash32_4 (hash.c:68-84), elementwise over broadcast uint32
    arrays — the draw hash of tree and list buckets."""
    a = jnp.asarray(a).astype(_U32)
    b = jnp.asarray(b).astype(_U32)
    c = jnp.asarray(c).astype(_U32)
    d = jnp.asarray(d).astype(_U32)
    a, b, c, d = jnp.broadcast_arrays(a, b, c, d)
    h = jnp.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d
    x = _const(h, 231232)
    y = _const(h, 1232)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


# ---------------------------------------------------------------------------
# crush_ln — 2^44*log2(x+1) in 48-bit fixed point (mapper.c:248-290)
# ---------------------------------------------------------------------------
#
# The table lookups are one-hot matmuls over 8-bit limbs, not gathers: TPU
# dynamic gathers from small int64 tables run ~0.06 Gops/s while an (N,129)
# bf16 one-hot matmul is exact (one-hot 0/1 and limbs < 2^8 are exact bf16;
# the f32 accumulator sums < 2^15) and far faster (measured on v5e).

@functools.lru_cache(maxsize=None)
def _ln_limb_operands_np():
    """Host-side limb tables; kept numpy so no device value is cached across
    jit traces (a cached tracer-context array leaks into later traces).

    Limbs are 8-bit so the matmul runs in bf16 (values < 256 and one-hot 0/1
    are exact in bf16; sums of <=129 such products stay < 2^15, exact in the
    f32 accumulator) — ~4x the f32 MXU rate for ~2x the MACs.  rh needs 7
    limbs (RH[0] = 2^48 exactly, a 49-bit value); lh/ll fit 6.  Layout:
    rh limbs 0..6, lh limbs 7..12; ll limbs 0..5."""
    rhlh = np.concatenate([
        np.stack([(rh_table() >> (8 * i)) & 0xFF for i in range(7)], -1),
        np.stack([(lh_table() >> (8 * i)) & 0xFF for i in range(6)], -1),
    ], axis=1).astype(np.float32)
    ll = np.stack([(ll_table() >> (8 * i)) & 0xFF
                   for i in range(6)], -1).astype(np.float32)
    return rhlh, ll


def _ln_limb_operands():
    rhlh, ll = _ln_limb_operands_np()
    return (jnp.asarray(rhlh, dtype=jnp.bfloat16),
            jnp.asarray(ll, dtype=jnp.bfloat16))


def _onehot_rows(idx, n_rows, table):
    """Exact limb lookup: (N,) int32 -> (N, limbs) f32 via the MXU."""
    oh = (idx[..., None] == jnp.arange(n_rows, dtype=jnp.int32)).astype(
        jnp.bfloat16)
    flat = oh.reshape(-1, n_rows)
    out = jax.lax.dot_general(
        flat, table, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)
    return out.reshape(*idx.shape, table.shape[1])


def _limbs_to_i64(v, lo, hi):
    r = v[..., lo].astype(jnp.int64)
    for i in range(lo + 1, hi):
        r = r + (v[..., i].astype(jnp.int64) << (8 * (i - lo)))
    return r


def crush_ln(xin):
    """Elementwise crush_ln over uint32 input arrays; returns int64."""
    x = (jnp.asarray(xin).astype(_U32) + jnp.uint32(1))
    low17 = x & jnp.uint32(0x1FFFF)
    # bits to normalize the mantissa into [0x8000, 0x18000); the C code computes
    # this with a shift loop (mapper.c:263-268), here via count-leading-zeros
    bitlen = jnp.uint32(32) - jax.lax.clz(low17 | jnp.uint32(1))
    bits = jnp.uint32(16) - bitlen
    needs_norm = (x & jnp.uint32(0x18000)) == 0
    xnorm = jnp.where(needs_norm, x << bits, x)
    iexpon = jnp.where(needs_norm, jnp.uint32(15) - bits, jnp.uint32(15))
    idx1 = (xnorm >> 8) << 1
    k = ((idx1 - jnp.uint32(256)) >> 1).astype(jnp.int32)
    rhlh_tab, ll_tab = _ln_limb_operands()
    rhlh = _onehot_rows(k, 129, rhlh_tab)
    rh = _limbs_to_i64(rhlh, 0, 7)
    lh = _limbs_to_i64(rhlh, 7, 13)
    # u64 wrap-around product; only bits [48..56) survive
    xl64 = (xnorm.astype(jnp.uint64) * rh.astype(jnp.uint64)) >> jnp.uint64(48)
    idx2 = (xl64 & jnp.uint64(0xFF)).astype(jnp.int32)
    ll = _limbs_to_i64(_onehot_rows(idx2, 256, ll_tab), 0, 6)
    return (iexpon.astype(jnp.int64) << 44) + ((lh + ll) >> 4)


_LN_2_48 = np.int64(1) << 48


def straw2_draws(x, ids, r, weights):
    """Per-item straw2 draws (mapper.c:334-359  generate_exponential_distribution).

    x : (...,) uint32 input values      ids : (S,) item ids
    r : (...,) replica numbers          weights : (S,) 16.16 fixed-point, >= 0
    returns (..., S) int64 draws; weight==0 items get S64_MIN.
    """
    x = jnp.asarray(x)
    r = jnp.asarray(r)
    ids = jnp.asarray(ids)
    w = jnp.asarray(weights).astype(jnp.int64)
    u = hash32_3(x[..., None], ids, r[..., None]) & jnp.uint32(0xFFFF)
    ln = crush_ln(u) - _LN_2_48
    # div64_s64 truncates toward zero; ln <= 0 and w > 0, so trunc == -((-ln)//w)
    draw = -((-ln) // jnp.maximum(w, 1))
    return jnp.where(w > 0, draw, jnp.int64(S64_MIN))


def straw2_choose_index(x, ids, r, weights):
    """Winning *position* in the bucket for each (x, r) — first max wins, matching
    the strict `>` comparison in bucket_straw2_choose (mapper.c:374-380)."""
    return jnp.argmax(straw2_draws(x, ids, r, weights), axis=-1)


# ---------------------------------------------------------------------------
# is_out — probabilistic rejection by the reweight vector (mapper.c:424-438)
# ---------------------------------------------------------------------------

def is_out(reweight, item, x):
    """reweight: (D,) 16.16 per-device; item: (...,) device ids; x: (...,) inputs.
    Ids beyond the reweight vector are out, like the weight_max guard in
    mapper.c:424-427 (jax gathers clamp, so the bound is checked explicitly)."""
    reweight = jnp.asarray(reweight)
    n = reweight.shape[0]
    oob = (item < 0) | (item >= n)
    w = reweight[jnp.clip(item, 0, n - 1)]
    keep_full = w >= 0x10000
    zero = w == 0
    h = hash32_2(x, item.astype(jnp.uint32)) & jnp.uint32(0xFFFF)
    keep_prob = h.astype(jnp.int64) < w.astype(jnp.int64)
    return oob | ~(keep_full | (~zero & keep_prob))


# ---------------------------------------------------------------------------
# flat firstn select: one straw2 bucket, n distinct replicas, retry ladder
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("numrep", "tries"))
def flat_firstn(x, ids, weights, reweight, *, numrep: int, tries: int = 51):
    """Batched CHOOSE_FIRSTN of ``numrep`` distinct devices from one straw2 bucket.

    Semantics match crush_choose_firstn (mapper.c:460-648) specialised to a flat
    map (single straw2 root of devices, modern tunables: choose_local_tries=0,
    choose_local_fallback_tries=0): for replica ``rep`` the draw uses
    r = rep + ftotal where ftotal counts this replica's collision/reject retries,
    and a replica is abandoned after ``tries`` failures (tries =
    choose_total_tries + 1 = 51 by default, mapper.c:906).

    x        : (N,) uint32 batch of inputs (pps values)
    ids      : (S,) device ids in the bucket
    weights  : (S,) 16.16 straw2 weights
    reweight : (D,) 16.16 per-device reweight vector (is_out test)
    returns  : (N, numrep) int32 device ids, CRUSH_ITEM_NONE (0x7fffffff) on failure
    """
    x = jnp.asarray(x).astype(_U32)
    ids = jnp.asarray(ids).astype(jnp.int32)
    n = x.shape[0]
    none = jnp.int32(0x7FFFFFFF)
    out = jnp.full((n, numrep), none, dtype=jnp.int32)

    def place_rep(rep, out):
        def cond(state):
            _, _, active = state
            return jnp.any(active)

        def body(state):
            sel, ftotal, active = state
            r = jnp.full((n,), rep, dtype=_U32) + ftotal.astype(_U32)
            pos = straw2_choose_index(x, ids, r, weights)
            item = ids[pos]
            collide = jnp.any(out == item[:, None], axis=1)
            rejected = is_out(reweight, item, x)
            bad = collide | rejected
            sel = jnp.where(active & ~bad, item, sel)
            ftotal = jnp.where(active & bad, ftotal + 1, ftotal)
            active = active & bad & (ftotal < tries)
            return sel, ftotal, active

        sel = jnp.full((n,), none, dtype=jnp.int32)
        ftotal = jnp.zeros((n,), dtype=jnp.int32)
        active = jnp.ones((n,), dtype=bool)
        sel, _, _ = jax.lax.while_loop(cond, body, (sel, ftotal, active))
        return out.at[:, rep].set(sel)

    for rep in range(numrep):
        out = place_rep(rep, out)
    return out
