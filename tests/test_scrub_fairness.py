"""The background-integrity fairness gate (e2e): continuous deep
scrub of every PG while the 4-tenant front runs at full rate — tenant
reservation attainment stays >= 0.95 of the scrub-off baseline, the
scrub traffic is visibly served from the background_best_effort class
(dump_qos_stats), and corruption injected mid-run is repaired AND
verified while the tenants keep hammering.

The data plane is made deterministic the same way test_qos_fairness
does it: a fixed per-op service delay wrapped around the shard
handler, so attainment depends on the dmclock arbitration, not on
host speed.  Tenant lanes ride the same machinery the S3 front stamps
(MOSDOp qos_tenant tags; the gateway-tagged variant is pinned by
test_qos_fairness's S3 scenario) — this gate adds the scrub storm on
top and measures the delta."""

from __future__ import annotations

import threading
import time

import pytest

from ceph_tpu.messages.osd_msgs import OP_WRITEFULL, OSDOpField
from ceph_tpu.objectstore import Transaction
from ceph_tpu.client.rados import ceph_str_hash_rjenkins
from ceph_tpu.osd.osdmap import pg_to_pgid
from ceph_tpu.tools.vstart import MiniCluster

pytestmark = pytest.mark.filterwarnings("ignore")

SERVICE_DELAY = 0.002


def _install_service_delay(osd, delay: float = SERVICE_DELAY) -> None:
    orig = osd.opwq._handler

    def slow(klass, item, served=None):
        time.sleep(delay)
        orig(klass, item, served)
    osd.opwq._handler = slow


def _set_profiles(client, profiles: dict[str, dict]) -> None:
    for tenant, p in profiles.items():
        rc, out = client.mon_command(
            {"prefix": "qos set", "tenant": tenant, **p})
        assert rc == 0, out


def _wait_profiles_applied(cluster, tenants, timeout=10.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(set(o._qos_profiles_applied) >= set(tenants)
               for o in cluster.osds.values()):
            return
        time.sleep(0.05)
    raise TimeoutError("qos_db never reached every osd")


def _gold_served(cluster) -> int:
    total = 0
    for osd in cluster.osds.values():
        d = osd.ctx.admin.execute("dump_qos_stats")
        row = d["classes"].get("client.gold")
        if row:
            total += sum(row["served"].values())
    return total


def _background_served(cluster) -> int:
    total = 0
    for osd in cluster.osds.values():
        d = osd.ctx.admin.execute("dump_qos_stats")
        row = d["classes"].get("background_best_effort")
        if row:
            total += sum(row["served"].values())
    return total


class _Pump:
    def __init__(self, client, pool: int, tenant: str, n_threads: int,
                 payload: bytes = b"x" * 64):
        self.client = client
        self.pool = pool
        self.tenant = tenant
        self.stop = threading.Event()
        self.counts = [0] * n_threads
        self.threads = [
            threading.Thread(target=self._run, args=(i, payload),
                             daemon=True, name=f"pump-{tenant}-{i}")
            for i in range(n_threads)]

    def _run(self, idx: int, payload: bytes) -> None:
        i = 0
        while not self.stop.is_set():
            oid = f"{self.tenant}-{idx}-{i % 4}"
            try:
                self.client.operate(
                    self.pool, oid,
                    [OSDOpField(OP_WRITEFULL, 0, len(payload),
                                payload)],
                    tenant=self.tenant)
            except (OSError, TimeoutError):
                continue
            self.counts[idx] += 1
            i += 1

    def start(self):
        for t in self.threads:
            t.start()
        return self

    def halt(self):
        self.stop.set()

    def join(self):
        for t in self.threads:
            t.join(timeout=15)

    @property
    def total(self) -> int:
        return sum(self.counts)


PROFILES = {
    "hog": {"weight": 8.0},
    "gold": {"reservation": 100.0, "weight": 0.01},
    "silver": {"weight": 2.0},
    "bronze": {"weight": 8.0, "limit": 50.0},
}

#: gold's demand comfortably exceeds its 100 ops/s reservation, so
#: the floor BINDS and attainment measures the scheduler, not the
#: pumps' closed-loop latency
PUMP_THREADS = {"hog": 8, "gold": 5, "silver": 4, "bronze": 4}

GOLD_RESERVATION = 100.0


def _attainment(rate: float) -> float:
    """Reservation attainment (the PR 9 bench definition): how much
    of the reserved floor the tenant actually drew, capped at 1 —
    demand above the floor is closed-loop noise, not QoS."""
    return min(rate, GOLD_RESERVATION) / GOLD_RESERVATION


def test_scrub_storm_keeps_tenant_reservations():
    cluster = MiniCluster(
        n_osds=3, ms_type="loopback",
        osd_conf={"osd_op_num_shards": 2,
                  "osd_scrub_verify_timeout": 10.0}).start()
    scrub_stop = threading.Event()
    scrub_threads = []
    try:
        cluster.wait_for_osd_count(3)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8, size=3)
        _set_profiles(client, PROFILES)
        _wait_profiles_applied(cluster, PROFILES)
        for osd in cluster.osds.values():
            _install_service_delay(osd)
        # a victim object with known bytes, corrupted on one replica
        # mid-run: the continuous sweep must find, repair, and VERIFY
        # it while the tenants keep the cluster saturated
        io = client.open_ioctx(pool)
        body = b"gate-truth" * 120
        io.write_full("gate-victim", body)
        for t, n in PUMP_THREADS.items():
            for idx in range(n):
                for i in range(4):
                    io.write_full(f"{t}-{idx}-{i}", b"x" * 64)
        time.sleep(0.3)
        m = cluster.mon.osdmap
        pg = pg_to_pgid(ceph_str_hash_rjenkins("gate-victim"),
                        m.pools[pool].pg_num)
        up, primary, _a, _ap = m.pg_to_up_acting_osds(pool, pg)
        victim_id = next(o for o in up if o != primary)
        cid = f"{pool}.{pg}"

        # warm the digest kernel on every live shape BEFORE anything
        # is measured: the gate is a steady-state arbitration claim,
        # and first-call jit compiles (attributed to the compile
        # ledger in production) would otherwise land inside the scrub
        # measurement window only
        for osd in cluster.osds.values():
            agg = osd.scrub_all_pgs()
            assert agg["clean"], agg
        warm_sweeps = {o: osd.ctx.admin.execute(
            "dump_scrub_stats")["sweeps"]
            for o, osd in cluster.osds.items()}

        pumps = {t: _Pump(client, pool, t, n).start()
                 for t, n in PUMP_THREADS.items()}
        try:
            # -- scrub-off baseline ---------------------------------
            time.sleep(1.0)                       # warmup
            g0 = _gold_served(cluster)
            t0 = time.perf_counter()
            time.sleep(2.5)
            base_rate = (_gold_served(cluster) - g0) \
                / (time.perf_counter() - t0)

            # -- continuous deep scrub of every PG: the production
            # driver (osd_scrub_auto_interval), hot-enabled ---------
            cluster.osds[victim_id].store.apply_transaction(
                Transaction().truncate(cid, "gate-victim", 0)
                .write(cid, "gate-victim", 0, b"gate-lies!" * 120))
            for osd in cluster.osds.values():
                osd.ctx.conf.set("osd_scrub_auto_interval", 0.5)
            time.sleep(1.5)                       # storm settles in
            g1 = _gold_served(cluster)
            t1 = time.perf_counter()
            time.sleep(2.5)
            scrub_rate = (_gold_served(cluster) - g1) \
                / (time.perf_counter() - t1)

            # repaired-and-verified DURING the run: pumps still
            # hammering, sweeps still going — poll the victim's store
            # until the scrub path restored it
            deadline = time.time() + 45.0
            while time.time() < deadline:
                if cluster.osds[victim_id].store.read(
                        cid, "gate-victim") == body:
                    break
                time.sleep(0.5)
            repaired_during_run = cluster.osds[victim_id].store.read(
                cid, "gate-victim") == body
        finally:
            for p in pumps.values():
                p.halt()
            scrub_stop.set()
            for osd in cluster.osds.values():
                try:
                    osd.ctx.conf.set("osd_scrub_auto_interval", 0.0)
                except Exception:
                    pass
            for p in pumps.values():
                p.join()

        # the acceptance gate: reservation attainment under the storm
        # >= 0.95 of the scrub-off baseline
        assert _attainment(scrub_rate) >= 0.95 * _attainment(
            base_rate), (base_rate, scrub_rate)
        # the floor was actually in play in both phases
        assert _attainment(base_rate) >= 0.95, base_rate

        # scrub was served from the background class, visibly
        assert _background_served(cluster) > 0
        # and the continuous driver really swept during the storm
        for o, osd in cluster.osds.items():
            st = osd.ctx.admin.execute("dump_scrub_stats")
            assert st["sweeps"] > warm_sweeps[o], (o, st)

        # the injected corruption was repaired AND verified during the
        # run (the victim replica read back as truth while the tenants
        # were still at full rate, and the cluster ledger shows a
        # verified repair with nothing unverified)
        assert repaired_during_run
        repaired = unverified = 0
        for osd in cluster.osds.values():
            st = osd.ctx.admin.execute("dump_scrub_stats")
            repaired += st["repaired"]
            unverified += st["repair_unverified"]
        assert repaired >= 1, (repaired, unverified)
        assert unverified == 0, (repaired, unverified)
        # every tenant progressed under the storm
        assert all(p.total > 0 for p in pumps.values()), {
            t: p.total for t, p in pumps.items()}
    finally:
        scrub_stop.set()
        cluster.stop()
