"""Real process separation: mons and OSDs as separate OS processes over
TCP (the reference's vstart.sh / ceph-helpers.sh tier — VERDICT round-2
item 4a).  Crash-kills a daemon process with SIGKILL mid-run and
verifies the cluster recovers when it restarts.
"""

from __future__ import annotations

import json
import time

import pytest

from ceph_tpu.tools.vstart import ProcCluster


def test_multiprocess_cluster(tmp_path):
    c = ProcCluster(n_osds=3, base_path=str(tmp_path)).start()
    try:
        client = c.client()
        c.wait_for_osd_count(3)
        pool = c.create_pool(client, pg_num="8", size="3")
        io = client.open_ioctx(pool)
        data = {f"mp-{i}": (f"proc-payload-{i}" * 20).encode()
                for i in range(20)}
        for k, v in data.items():
            io.write_full(k, v)
        for k, v in data.items():
            assert io.read(k) == v

        # crash an OSD process outright; the remaining two keep serving
        c.kill_osd(1)
        deadline = time.time() + 30
        while time.time() < deadline:
            rc, out = client.mon_command({"prefix": "status"})
            if rc == 0 and json.loads(out)["num_up_osds"] == 2:
                break
            time.sleep(0.25)
        io.write_full("after-kill", b"still-serving")
        assert io.read("after-kill") == b"still-serving"

        # restart it (same store directory): recovery converges
        c.run_osd(1)
        c.wait_for_osd_count(3)
        for k, v in data.items():
            assert io.read(k) == v
        assert io.read("after-kill") == b"still-serving"
    finally:
        c.stop()


def test_multiprocess_ec_pool(tmp_path):
    c = ProcCluster(n_osds=4, base_path=str(tmp_path)).start()
    try:
        client = c.client()
        c.wait_for_osd_count(4)
        pool = c.create_pool(client, pg_num="8", pool_type="erasure",
                             k="2", m="2")
        io = client.open_ioctx(pool)
        payload = bytes(range(256)) * 64
        io.write_full("ec-proc", payload)
        assert io.read("ec-proc") == payload
    finally:
        c.stop()


def test_dcn_two_process_mesh():
    """DCN: two OS processes, half the virtual devices each, one global
    jax.distributed mesh; the sharded GF encode's reduction crosses the
    process boundary and the workers cross-check over TCP messengers
    (SURVEY §5 ICI-within / DCN-between mapping)."""
    from ceph_tpu.parallel.dcn import run_dcn_pair
    run_dcn_pair(4)


def test_rgw_daemon_process(tmp_path):
    """The radosgw deployment shell (daemon_main --role rgw): a
    separate OS process serving authenticated S3 over a TCP cluster."""
    import hashlib
    import http.client
    import time as _time

    from ceph_tpu.rgw_rest import derive_s3_credentials, sign_request

    c = ProcCluster(n_osds=3, base_path=str(tmp_path),
                    auth_key="rgw-proc-key").start()
    try:
        client = c.client()
        c.wait_for_osd_count(3)
        pool = c.create_pool(client, pg_num=2, size=2)
        addr = c.run_rgw(pool)
        # same derivation the daemon applied (provision_from_cephx)
        access, secret = derive_s3_credentials("rgw-proc-key")
        host, port = addr.rsplit(":", 1)

        def req(method, path, body=b""):
            sha = hashlib.sha256(body).hexdigest()
            amz = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
            hdrs = {"Host": addr, "x-amz-date": amz,
                    "x-amz-content-sha256": sha,
                    "Authorization": sign_request(
                        method, path, "", {"host": addr,
                                           "x-amz-date": amz,
                                           "x-amz-content-sha256": sha},
                        sha, access, secret)}
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=30)
            conn.request(method, path, body=body, headers=hdrs)
            r = conn.getresponse()
            out = (r.status, r.read())
            conn.close()
            return out

        assert req("PUT", "/procbucket")[0] == 200
        assert req("PUT", "/procbucket/hello",
                   b"from another process")[0] == 200
        st, body = req("GET", "/procbucket/hello")
        assert st == 200 and body == b"from another process"
    finally:
        c.stop()
