"""Sharded multi-chip execution of the EC + CRUSH data path.

The reference's distributed write (SURVEY.md §3.3) is: place the PG with CRUSH,
encode the stripe into k+m shards, fan the shards out to OSDs over the cluster
messenger, and on recovery fan k shards back in.  On a TPU mesh the same step is:

    place   flat straw2 firstn, batched over PGs     [dp x ec sharded, elementwise]
    encode  batched GF(2^8) matmul on the MXU        [stripes sharded]
    scatter shard axis resharded over the ec axis    [XLA all_to_all on ICI]
    recover all_gather shards along ec + decode      [explicit shard_map collective]
    stats   device utilization histogram             [psum over the whole mesh]

Everything is one jitted function over a ("dp", "ec") Mesh; XLA inserts the
collectives from the sharding annotations, exactly the scaling-book recipe.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma in jax 0.8
_CHECK_KW = ("check_vma" if "check_vma" in
             inspect.signature(_shard_map).parameters else "check_rep")


def shard_map(f, **kwargs):
    if "check_rep" in kwargs:
        kwargs[_CHECK_KW] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)

from ceph_tpu.gf.matrix import recovery_matrix
from ceph_tpu.gf.tables import bit_matrix
from ceph_tpu.ops.gf_kernel import _encode_xla as _encode_impl
from ceph_tpu.ops.crush_kernel import flat_firstn


def sharded_encode(mesh, coeff: np.ndarray, data, dot_dtype=jnp.bfloat16):
    """Encode with stripes sharded across every device in the mesh.

    data: (S, k, B) uint8, S divisible by mesh size.  Pure data parallelism —
    the TPU analog of ECUtil's per-stripe loop (src/osd/ECUtil.cc:136) run on
    all chips at once.
    """
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    w = jnp.asarray(bit_matrix(coeff))
    spec = NamedSharding(mesh, P(("dp", "ec"), None, None))
    data = jax.device_put(jnp.asarray(data, dtype=jnp.uint8), spec)
    fn = jax.jit(
        functools.partial(_encode_impl, k=k, m=m, dot_dtype=dot_dtype),
        out_shardings=spec,
    )
    return fn(w, data)


def make_cluster_step(mesh, gen: np.ndarray, ids, weights, reweight,
                      *, numrep: int, erasures: tuple[int, ...],
                      dot_dtype=jnp.bfloat16):
    """Build the flagship distributed step: place + encode + scatter + recover.

    gen      : (k+m, k) uint8 systematic generator matrix (identity on top).
    ids      : (S,) device ids of the flat straw2 root     (placement operand)
    weights  : (S,) 16.16 straw2 weights
    reweight : (D,) 16.16 reweight vector
    numrep   : replicas to place per PG
    erasures : static chunk indices simulated lost; recovery rebuilds them from
               the first k surviving chunks via an all_gather over the ec axis
               (the MOSDECSubOpRead fan-in, ECBackend.cc:2301 analog).

    Returns step(xs, data) -> dict with placements, parity, recovered chunks,
    utilization histogram, and mismatches (recovered-vs-original check, 0 when
    the math is right).  xs: (N,) uint32; data: (S, k, B) uint8.
    """
    gen = np.asarray(gen, dtype=np.uint8)
    k = gen.shape[1]
    m = gen.shape[0] - k
    n_chunks = k + m
    ec_size = mesh.shape["ec"]
    if n_chunks % ec_size:
        raise ValueError(f"k+m={n_chunks} not divisible by ec axis {ec_size}")
    coding = gen[k:]
    w_enc = jnp.asarray(bit_matrix(coding))
    chosen = [i for i in range(n_chunks) if i not in set(erasures)][:k]
    rmat = recovery_matrix(gen, chosen, list(erasures))
    w_rec = jnp.asarray(bit_matrix(rmat))
    n_lost = len(erasures)
    chosen_arr = jnp.asarray(chosen, dtype=jnp.int32)
    lost_arr = jnp.asarray(list(erasures), dtype=jnp.int32)

    ids = jnp.asarray(ids, dtype=jnp.int32)
    weights = jnp.asarray(weights, dtype=jnp.int64)
    reweight = jnp.asarray(reweight, dtype=jnp.int64)
    max_dev = int(reweight.shape[0])

    batch_spec = NamedSharding(mesh, P(("dp", "ec")))
    stripe_spec = NamedSharding(mesh, P(("dp", "ec"), None, None))
    shard_spec = NamedSharding(mesh, P("dp", "ec", None))  # chunk axis over ec
    repl = NamedSharding(mesh, P())

    def recover(chunks):
        """chunks block: (S/dp, n_chunks/ec, B) — gather shards, rebuild lost."""
        full = jax.lax.all_gather(chunks, "ec", axis=1, tiled=True)
        surv = jnp.take(full, chosen_arr, axis=1)
        rebuilt = _encode_impl(w_rec, surv, k=k, m=n_lost, dot_dtype=dot_dtype)
        truth = jnp.take(full, lost_arr, axis=1)
        local_bad = jnp.sum(rebuilt != truth)
        # every ec shard computes the same comparison post-gather; count it once
        local_bad = jnp.where(jax.lax.axis_index("ec") == 0, local_bad, 0)
        bad = jax.lax.psum(local_bad, ("dp", "ec"))
        return rebuilt, bad

    recover_sharded = shard_map(
        recover, mesh=mesh,
        in_specs=(P("dp", "ec", None),),
        out_specs=(P("dp", None, None), P()),
        check_rep=False,
    )

    def step(xs, data):
        placements = flat_firstn(xs, ids, weights, reweight,
                                 numrep=numrep, tries=51)
        parity = _encode_impl(w_enc, data, k=k, m=m, dot_dtype=dot_dtype)
        chunks = jnp.concatenate([data, parity], axis=1)  # (S, k+m, B)
        # reshard: stripes over dp, chunk fan-out over ec (the shard scatter)
        chunks = jax.lax.with_sharding_constraint(chunks, shard_spec)
        rebuilt, mismatches = recover_sharded(chunks)
        valid = placements != 0x7FFFFFFF
        util = jnp.sum(
            jax.nn.one_hot(jnp.where(valid, placements, 0), max_dev,
                           dtype=jnp.int32) * valid[..., None].astype(jnp.int32),
            axis=(0, 1),
        )
        return {
            "placements": placements,
            "parity": parity,
            "rebuilt": rebuilt,
            "utilization": util,
            "mismatches": mismatches,
        }

    return jax.jit(
        step,
        in_shardings=(batch_spec, stripe_spec),
        out_shardings={
            "placements": batch_spec,
            "parity": stripe_spec,
            "rebuilt": NamedSharding(mesh, P("dp", None, None)),
            "utilization": repl,
            "mismatches": repl,
        },
    )
