"""Manager daemon package: the module host (daemon), the MgrModule
framework (module), and the module ecosystem (modules/)."""

from ceph_tpu.mgr.daemon import MgrDaemon, MMgrBeacon, MMgrReport
from ceph_tpu.mgr.module import MgrModule, ModuleHost

__all__ = ["MgrDaemon", "MMgrBeacon", "MMgrReport", "MgrModule",
           "ModuleHost"]
