"""ICI messenger stack — the device mesh as a transport behind the
Messenger API (SURVEY §5's mapping: the reference's pluggable
NetworkStack family {posix, rdma, dpdk} becomes {tcp, loopback, ICI},
with the entity-addressed Messenger surface unchanged).

Control frames (op headers, acks, maps, peering) ride the in-process
queue exactly like the loopback stack.  BULK PAYLOADS — EC shard chunks
in MOSDECSubOpWrite / MOSDECSubOpReadReply — are split out of the frame
and moved through the jax device mesh instead: the sender places the
chunk on the RECEIVER's device (jax.device_put — an ICI hop on real
multi-chip hardware, a real cross-device placement on the CPU test
mesh), and the frame carries only a token the receiver redeems.  The
OSD daemons are completely unaware: the stack IS the abstraction, so
the EC data path and the mesh data path are one code path.

Device assignment: osd.N <-> jax.devices()[N % ndevices] — each OSD
"owns" a mesh position, so a k+m shard fan-out lands one chunk per
device, exactly the sharded-encode layout of parallel/sharded.py.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .loopback import LoopbackConnection, LoopbackMessenger
from .message import Message
from .messenger import EntityName

_MARKER = b"\x00ICI\x00"


class IciTransport:
    """Process-wide staged-buffer registry (the 'wire' is device HBM).

    Lifecycle hardening: every staged buffer carries a deadline.  A
    buffer nobody redeems (its frame was dropped with a dying daemon)
    reaps after TTL seconds — device memory cannot leak to lost
    messages.  A REDEEMED buffer lingers for GRACE seconds before
    reaping, so a stateful connection resending its backlog (frames
    already delivered once) can redeem the same token again instead of
    erroring; after the grace the resent frame is dropped like any
    transport loss and the op-level retry repairs it."""

    _instance = None
    _lock = threading.Lock()

    #: seconds an unredeemed staged buffer survives (message lost)
    TTL = 30.0
    #: seconds a redeemed buffer stays redeemable (resend window)
    GRACE = 10.0

    def __init__(self):
        import jax
        self.jax = jax
        self.devices = jax.devices()
        self._bufs: dict[int, dict] = {}
        self._seq = 0
        self._reg_lock = threading.Lock()
        self.bytes_staged = 0      # cumulative
        self.transfers = 0         # cumulative
    # gauge: currently staged, unredeemed

    def outstanding(self) -> tuple[int, int]:
        """(buffers, bytes) staged and not yet redeemed (after a reap)."""
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            live = [e for e in self._bufs.values()
                    if e["redeemed_at"] is None]
            return len(live), sum(e["nbytes"] for e in live)

    def _reap_locked(self, now: float) -> None:
        dead = [t for t, e in self._bufs.items()
                if (e["redeemed_at"] is not None
                    and now - e["redeemed_at"] > self.GRACE)
                or (e["redeemed_at"] is None
                    and now - e["staged_at"] > self.TTL)]
        for t in dead:
            del self._bufs[t]

    @classmethod
    def instance(cls) -> "IciTransport":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def device_for(self, name: EntityName):
        idx = name.id if name.type == "osd" else 0
        return self.devices[idx % len(self.devices)]

    def stage(self, chunk: bytes, peer: EntityName) -> bytes:
        """Place the payload on the peer's device; returns the token the
        frame carries instead of the bytes."""
        import jax.numpy as jnp
        arr = jnp.asarray(np.frombuffer(chunk, dtype=np.uint8))
        buf = self.jax.device_put(arr, self.device_for(peer))
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            self._seq += 1
            token = self._seq
            self._bufs[token] = {"buf": buf, "nbytes": len(chunk),
                                 "staged_at": now, "redeemed_at": None}
            self.bytes_staged += len(chunk)
            self.transfers += 1
        return _MARKER + token.to_bytes(8, "little")

    def redeem(self, blob: bytes) -> bytes:
        token = int.from_bytes(blob[len(_MARKER):], "little")
        now = time.monotonic()
        with self._reg_lock:
            self._reap_locked(now)
            entry = self._bufs.get(token)
            if entry is not None and entry["redeemed_at"] is None:
                entry["redeemed_at"] = now
            buf = entry["buf"] if entry is not None else None
        if buf is None:
            raise KeyError(f"ici token {token} expired or unknown")
        return np.asarray(buf).tobytes()

    @staticmethod
    def is_token(blob: bytes) -> bool:
        return blob.startswith(_MARKER)


def _bulk_field(msg: Message):
    """The bulk-payload attribute of data-plane messages, if any."""
    from ceph_tpu.messages.osd_msgs import (
        MOSDECSubOpReadReply, MOSDECSubOpWrite)
    from ceph_tpu.osd.daemon import MOSDPGPush
    if isinstance(msg, (MOSDECSubOpWrite, MOSDECSubOpReadReply)):
        return "chunk"
    if isinstance(msg, MOSDPGPush):
        return "data"
    return None


class IciConnection(LoopbackConnection):
    #: payloads below this stay in the control frame
    BULK_THRESHOLD = 512

    def send_message(self, msg: Message) -> None:
        field = _bulk_field(msg)
        if field is not None and self.peer_name is not None:
            payload = getattr(msg, field)
            if (len(payload) >= self.BULK_THRESHOLD
                    and not IciTransport.is_token(payload)):
                setattr(msg, field,
                        IciTransport.instance().stage(payload,
                                                      self.peer_name))
        super().send_message(msg)


class IciMessenger(LoopbackMessenger):
    """Loopback control plane + device-mesh data plane."""

    def _make_connection(self, addr: str, peer_name):
        return IciConnection(self, addr, peer_name)

    def deliver(self, msg: Message) -> bool:
        field = _bulk_field(msg)
        if field is not None:
            payload = getattr(msg, field)
            if IciTransport.is_token(payload):
                try:
                    setattr(msg, field,
                            IciTransport.instance().redeem(payload))
                except KeyError:
                    # the staged buffer expired (sender died long ago or
                    # the resend window closed): transport loss — drop
                    # the frame, the op-level retry resends fresh bytes
                    from ceph_tpu.common.logging import dout
                    dout("ms", 5, "ici: dropping frame with expired token")
                    return True
        return super().deliver(msg)
