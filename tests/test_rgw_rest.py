"""RGW S3 REST frontend over a MiniCluster: real HTTP round-trips with
AWS SigV4 signing (rgw_rest_s3.cc / rgw_asio_frontend.cc analog).
"""

from __future__ import annotations

import hashlib
import http.client
import re
import time
import urllib.parse

import pytest

from ceph_tpu.rgw_rest import RgwRestServer, sign_request
from ceph_tpu.tools.vstart import MiniCluster

AUTH_KEY = b"rgw-cluster-secret"


class S3Client:
    """Minimal SigV4-signing HTTP client (what aws-cli/boto would do)."""

    def __init__(self, addr: str, access: str, secret: str):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.access = access
        self.secret = secret

    def request(self, method: str, path: str, query: str = "",
                body: bytes = b"", meta: dict | None = None):
        payload_sha = hashlib.sha256(body).hexdigest()
        amzdate = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {"Host": f"{self.host}:{self.port}",
                   "x-amz-date": amzdate,
                   "x-amz-content-sha256": payload_sha}
        headers["Authorization"] = sign_request(
            method, path, query,
            {"host": headers["Host"], "x-amz-date": amzdate,
             "x-amz-content-sha256": payload_sha},
            payload_sha, self.access, self.secret)
        for k, v in (meta or {}).items():
            headers[f"x-amz-meta-{k}"] = v
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        url = path + (f"?{query}" if query else "")
        conn.request(method, url, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        out = (resp.status, data, dict(resp.getheaders()))
        conn.close()
        return out


@pytest.fixture(scope="module")
def s3():
    c = MiniCluster(n_osds=3, auth_key=AUTH_KEY).start()
    c.wait_for_osd_count(3)
    client = c.client()
    pool = c.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    srv = RgwRestServer(io).start()
    access, secret = srv.provision_from_cephx(AUTH_KEY)
    yield S3Client(srv.addr, access, secret)
    srv.shutdown()
    c.stop()


def test_bucket_and_object_roundtrip(s3):
    status, _, _ = s3.request("PUT", "/photos")
    assert status == 200
    body = b"jpeg-bytes" * 100
    status, _, hdrs = s3.request("PUT", "/photos/cat.jpg", body=body,
                                 meta={"owner": "alice"})
    assert status == 200
    assert hdrs["ETag"] == f'"{hashlib.md5(body).hexdigest()}"'
    status, got, hdrs = s3.request("GET", "/photos/cat.jpg")
    assert status == 200 and got == body
    assert hdrs.get("x-amz-meta-owner") == "alice"
    status, _, _ = s3.request("HEAD", "/photos/cat.jpg")
    assert status == 200
    status, _, _ = s3.request("DELETE", "/photos/cat.jpg")
    assert status == 204
    status, got, _ = s3.request("GET", "/photos/cat.jpg")
    assert status == 404 and b"NoSuchKey" in got


def test_list_pagination(s3):
    s3.request("PUT", "/paged")
    for i in range(7):
        s3.request("PUT", f"/paged/k{i:02d}", body=b"x")
    keys, token, pages = [], "", 0
    while True:
        q = "list-type=2&max-keys=3" + (
            f"&continuation-token={token}" if token else "")
        status, xml, _ = s3.request("GET", "/paged", query=q)
        assert status == 200
        keys += re.findall(r"<Key>([^<]+)</Key>", xml.decode())
        pages += 1
        m = re.search(r"<NextContinuationToken>([^<]+)<", xml.decode())
        if not m:
            assert b"<IsTruncated>false" in xml
            break
        token = m.group(1)
    assert keys == [f"k{i:02d}" for i in range(7)]
    assert pages == 3

    status, xml, _ = s3.request("GET", "/paged",
                                query="list-type=2&prefix=k0")
    got = re.findall(r"<Key>([^<]+)</Key>", xml.decode())
    assert got == [f"k0{i}" for i in range(7)]


def test_multipart_upload(s3):
    s3.request("PUT", "/mpb")
    status, xml, _ = s3.request("POST", "/mpb/big.bin", query="uploads")
    assert status == 200
    uid = re.search(r"<UploadId>([^<]+)<", xml.decode()).group(1)
    parts = [b"A" * 5000, b"B" * 5000, b"C" * 1234]
    etags = []
    for i, p in enumerate(parts, start=1):
        status, _, hdrs = s3.request(
            "PUT", "/mpb/big.bin",
            query=f"partNumber={i}&uploadId={uid}", body=p)
        assert status == 200
        etags.append(hdrs["ETag"].strip('"'))
    complete = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>\"{e}\"</ETag></Part>"
        for i, e in enumerate(etags, start=1)) + \
        "</CompleteMultipartUpload>"
    status, xml, _ = s3.request("POST", "/mpb/big.bin",
                                query=f"uploadId={uid}",
                                body=complete.encode())
    assert status == 200
    status, got, _ = s3.request("GET", "/mpb/big.bin")
    assert status == 200 and got == b"".join(parts)
    # staged parts are gone: the bucket lists only the final object
    status, xml, _ = s3.request("GET", "/mpb", query="list-type=2")
    assert re.findall(r"<Key>([^<]+)</Key>", xml.decode()) == ["big.bin"]


def test_multipart_abort(s3):
    s3.request("PUT", "/mpa")
    _, xml, _ = s3.request("POST", "/mpa/tmp.bin", query="uploads")
    uid = re.search(r"<UploadId>([^<]+)<", xml.decode()).group(1)
    s3.request("PUT", "/mpa/tmp.bin",
               query=f"partNumber=1&uploadId={uid}", body=b"zzz")
    status, _, _ = s3.request("DELETE", "/mpa/tmp.bin",
                              query=f"uploadId={uid}")
    assert status == 204
    status, xml, _ = s3.request(
        "POST", "/mpa/tmp.bin", query=f"uploadId={uid}",
        body=b"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
             b"</Part></CompleteMultipartUpload>")
    assert status == 404 and b"NoSuchUpload" in xml


def test_auth_rejection(s3):
    bad = S3Client(f"{s3.host}:{s3.port}", s3.access, "wrong-secret")
    status, xml, _ = bad.request("GET", "/photos", query="list-type=2")
    assert status == 403 and b"SignatureDoesNotMatch" in xml

    conn = http.client.HTTPConnection(s3.host, s3.port, timeout=10)
    conn.request("GET", "/photos")
    resp = conn.getresponse()
    body = resp.read()
    assert resp.status == 403 and b"AccessDenied" in body
    conn.close()


def test_bucket_errors(s3):
    status, xml, _ = s3.request("GET", "/nosuch", query="list-type=2")
    assert status == 404 and b"NoSuchBucket" in xml
    s3.request("PUT", "/full")
    s3.request("PUT", "/full/x", body=b"1")
    status, xml, _ = s3.request("DELETE", "/full")
    assert status == 409 and b"BucketNotEmpty" in xml
    status, xml, _ = s3.request("PUT", "/full")
    assert status == 409 and b"BucketAlreadyExists" in xml


def test_encoded_object_keys(s3):
    """Keys needing percent-encoding sign and round-trip (the S3
    no-double-encode canonical URI rule)."""
    s3.request("PUT", "/enc")
    path = "/enc/" + urllib.parse.quote("report 2026/α.txt", safe="")
    status, _, _ = s3.request("PUT", path, body=b"spaced")
    assert status == 200
    status, got, _ = s3.request("GET", path)
    assert status == 200 and got == b"spaced"
    status, xml, _ = s3.request("GET", "/enc", query="list-type=2")
    assert "report 2026/α.txt" in xml.decode()


def test_reserved_multipart_prefix_rejected(s3):
    s3.request("PUT", "/resv")
    status, xml, _ = s3.request("PUT", "/resv/.mp.sneaky", body=b"x")
    assert status == 400 and b"InvalidArgument" in xml
