"""CRUSH device classes via shadow hierarchies.

The reference (CrushWrapper::populate_classes / device_class_clone,
src/crush/CrushWrapper.cc) implements `step take <root> class <c>` by
cloning the hierarchy per class: each shadow bucket keeps only the
devices of that class (and the shadow clones of its child buckets),
with weights recomputed bottom-up.  Rules then `take` the shadow root
— the mapper itself is completely class-unaware, which is exactly why
the batched TPU kernels need no changes to support classes.

`populate_classes` builds/refreshes the shadows and records them in
`CrushMap.class_bucket[(orig_id, class_name)] = shadow_id`; the text
compiler resolves `step take X class c` through that table, and the
decompiler maps shadow takes back to the class-qualified form.
"""

from __future__ import annotations

from .builder import make_bucket
from .types import CrushMap


def populate_classes(m: CrushMap, device_classes: dict[int, str]) -> None:
    """Build one shadow tree per device class.

    device_classes: device id -> class name (devices absent from the
    map belong to no class and appear in no shadow).  Shadow buckets
    get fresh negative ids; empty shadows (a host with no devices of
    the class anywhere beneath it) are kept with weight 0, like the
    reference — `take` on them simply maps nothing.
    """
    classes = sorted(set(device_classes.values()))
    # refresh: drop any previous shadow tree first — recloning on top of
    # stale shadows would clone shadows-of-shadows and leak buckets.
    # Remember what each old shadow stood for so rules already resolved
    # to a shadow id can be re-pointed after the rebuild (ids shift)
    old_shadow_of = {sid: key for key, sid in m.class_bucket.items()}
    for sid in old_shadow_of:
        idx = -1 - sid
        if 0 <= idx < len(m.buckets):
            m.buckets[idx] = None
    m.class_bucket = {}
    for cname in classes:
        # bottom-up clone: children before parents.  Iterate buckets in
        # dependency order by resolving recursively with memoization.
        shadow_of: dict[int, int] = {}

        def clone(bid: int, cname=cname, shadow_of=shadow_of) -> int:
            if bid in shadow_of:
                return shadow_of[bid]
            b = m.bucket(bid)
            items, weights = [], []
            for it, w in zip(b.items, b.item_weights):
                if it >= 0:
                    if device_classes.get(it) == cname:
                        items.append(it)
                        weights.append(w)
                else:
                    sid = clone(it)
                    sw = m.bucket(sid).weight
                    if sw > 0:
                        items.append(sid)
                        weights.append(sw)
            shadow = make_bucket(m.next_bucket_id(), b.alg, b.type,
                                 items, weights)
            shadow.hash = b.hash
            m.add_bucket(shadow)
            shadow_of[bid] = shadow.id
            m.class_bucket[(bid, cname)] = shadow.id
            return shadow.id

        shadow_ids = set(m.class_bucket.values())
        for b in list(m.buckets):
            if b is not None and (b.id, cname) not in m.class_bucket \
                    and b.id not in shadow_ids:
                clone(b.id)
                shadow_ids = set(m.class_bucket.values())

    # re-point rules that resolved to a previous generation's shadow id:
    # shadow ids shift across a refresh, and a stale TAKE would land on
    # a freed slot (or, worse, another class's new shadow)
    from .types import RULE_TAKE
    for r in m.rules:
        if r is None:
            continue
        for s in r.steps:
            if s.op == RULE_TAKE and s.arg1 in old_shadow_of:
                s.arg1 = m.class_bucket.get(old_shadow_of[s.arg1],
                                            s.arg1)


def shadow_to_class(m: CrushMap) -> dict[int, tuple[int, str]]:
    """shadow id -> (original id, class name) — the decompiler's view."""
    return {sid: (orig, cname)
            for (orig, cname), sid in m.class_bucket.items()}
