"""iostat module (src/pybind/mgr/iostat analog): cluster I/O rates from
successive MMgrReport counter samples."""

from __future__ import annotations

import json
import time

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "iostat"
    COMMANDS = [{"prefix": "iostat",
                 "help": "per-osd and total wr/rd op rates"}]

    def rates(self) -> dict:
        """Per-osd and total wr/rd ops per second over each osd's last
        report interval."""
        out: dict = {"osds": {}, "total_wr_ops_s": 0.0,
                     "total_rd_ops_s": 0.0}
        now = time.time()
        samples = self.get("io_samples")
        for osd, (t, counters) in samples["current"].items():
            if now - t > 10.0:
                # a dead osd's last interval is not a current rate:
                # stale reporters drop out instead of reporting their
                # final rate forever
                continue
            prev = samples["prev"].get(osd)
            if prev is None:
                continue
            pt, pc = prev
            dt = t - pt
            if dt <= 1e-3:
                # two reports bunched within a millisecond (timer
                # starvation under load) are not a rate window
                continue
            wr = (counters.get("op_w", 0) - pc.get("op_w", 0)) / dt
            rd = (counters.get("op_r", 0) - pc.get("op_r", 0)) / dt
            out["osds"][osd] = {"wr_ops_s": round(max(wr, 0.0), 3),
                                "rd_ops_s": round(max(rd, 0.0), 3),
                                "interval_s": round(dt, 3)}
            out["total_wr_ops_s"] += max(wr, 0.0)
            out["total_rd_ops_s"] += max(rd, 0.0)
        out["total_wr_ops_s"] = round(out["total_wr_ops_s"], 3)
        out["total_rd_ops_s"] = round(out["total_rd_ops_s"], 3)
        return out

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        return json.dumps(self.rates()), 0
