"""Performance counters (src/common/perf_counters.h:59,150 analog).

Components build a counter set with PerfCountersBuilder (u64 counters,
time-averages with count+sum, histograms), registered in the context's
collection and dumped via the admin socket (`perf dump`) — the surface the
reference's mgr scrapes via MMgrReport.
"""

from __future__ import annotations

import threading

U64 = "u64"
TIME_AVG = "time_avg"
HISTOGRAM = "histogram"


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        # analysis: allow[bare-lock] -- per-counter-set leaf lock on every hot-path inc(); never held across a call
        self._lock = threading.Lock()
        self._types: dict[str, str] = {}
        self._u64: dict[str, int] = {}
        self._avg: dict[str, tuple[int, float]] = {}   # (count, sum)
        self._hist: dict[str, list[int]] = {}
        self._hist_bounds: dict[str, list[float]] = {}
        self._hist_sum: dict[str, float] = {}

    # -- mutation -------------------------------------------------------------

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._u64[name] += amount

    def dec(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._u64[name] -= amount

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._u64[name] = value

    def tinc(self, name: str, seconds: float) -> None:
        """Accumulate a latency sample (perf_counters time avg)."""
        with self._lock:
            c, s = self._avg[name]
            self._avg[name] = (c + 1, s + seconds)

    def hinc(self, name: str, value: float) -> None:
        with self._lock:
            bounds = self._hist_bounds[name]
            # bounds are UPPER-inclusive (`le`) limits, matching the
            # Prometheus bucket model the exposition emits them as
            bucket = sum(1 for b in bounds if value > b)
            self._hist[name][bucket] += 1
            self._hist_sum[name] += value

    # -- reading --------------------------------------------------------------

    def value(self, name: str):
        with self._lock:
            t = self._types[name]
            if t == U64:
                return self._u64[name]
            if t == TIME_AVG:
                return self._avg[name]
            return list(self._hist[name])

    def avg(self, name: str) -> float:
        c, s = self._avg[name]
        return s / c if c else 0.0

    def dump(self) -> dict:
        """`perf dump` shape: {counter: value or {avgcount, sum}}."""
        with self._lock:
            out = {}
            for n, t in self._types.items():
                if t == U64:
                    out[n] = self._u64[n]
                elif t == TIME_AVG:
                    c, s = self._avg[n]
                    out[n] = {"avgcount": c, "sum": s}
                else:
                    out[n] = {"bounds": self._hist_bounds[n],
                              "buckets": list(self._hist[n]),
                              "sum": self._hist_sum[n]}
            return out


class PerfCountersBuilder:
    """Declare-then-build, like the reference's add_u64/add_time_avg chain."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64(self, name: str, description: str = ""):
        self._pc._types[name] = U64
        self._pc._u64[name] = 0
        return self

    def add_time_avg(self, name: str, description: str = ""):
        self._pc._types[name] = TIME_AVG
        self._pc._avg[name] = (0, 0.0)
        return self

    def add_histogram(self, name: str, bounds: list[float],
                      description: str = ""):
        self._pc._types[name] = HISTOGRAM
        self._pc._hist_bounds[name] = list(bounds)
        self._pc._hist[name] = [0] * (len(bounds) + 1)
        self._pc._hist_sum[name] = 0.0
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """All counter sets of one context (perf_counters_collection_t)."""

    def __init__(self):
        # analysis: allow[bare-lock] -- collection registry leaf lock
        self._lock = threading.Lock()
        self._sets: dict[str, PerfCounters] = {}

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._sets[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._sets.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        with self._lock:
            return self._sets.get(name)

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}
