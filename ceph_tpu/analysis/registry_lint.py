"""Registry-consistency lints (check family ``registry``).

Two sub-checks keyed by the codebase's two central registries:

* ``conf-key`` — every string-literal ``*.conf.get("key")`` /
  ``conf.get("key")`` / ``conf.set("key", ..)`` must name an option in
  ``common/config.py``'s table (``OPTIONS`` plus every
  ``register_options([Option(..)])`` call in the tree).  A typo'd key
  raises ``KeyError`` at runtime — on whatever rarely-exercised path
  reads it first.

* ``perf-counter`` — every counter mutation (``.inc/.dec/.tinc/
  .hinc(name)``, plus ``.set(name, v)`` on a ``perf``-named receiver)
  must name a counter registered via some ``PerfCountersBuilder``
  chain in the tree (an unregistered name raises ``KeyError`` inside
  the counter lock at runtime).  Membership is checked against the
  union of every declared set — object-precise matching is
  undecidable here, and a union miss is always a real bug.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

_MUTATORS = {"inc", "dec", "tinc", "hinc"}


def _option_names(index: TreeIndex) -> set:
    names: set = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                ch = name_chain(node.func)
                if ch and ch[-1] == "Option" and node.args and \
                        isinstance(node.args[0], ast.Constant):
                    names.add(node.args[0].value)
    return names


def _registered_counters(index: TreeIndex) -> set:
    """Union of every counter name declared by a builder chain."""
    union: set = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            # builder chains hang Attribute off a Call
            # (PerfCountersBuilder(..).add_u64("a").add_u64("b")), so
            # match on the method attribute alone, not a name chain
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("add_u64", "add_time_avg",
                                       "add_histogram") and \
                    node.args and isinstance(node.args[0],
                                             ast.Constant):
                union.add(node.args[0].value)
    return union


def check(index: TreeIndex):
    findings = []
    options = _option_names(index)
    counters = _registered_counters(index)
    for relpath, mod in sorted(index.by_path.items()):
        if mod.modname.endswith("common.config"):
            continue     # the table itself (defaults, casts, errors)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain or len(chain) < 2:
                continue
            tail = chain[-1]
            arg0 = node.args[0] if node.args else None
            literal = arg0.value if isinstance(arg0, ast.Constant) \
                and isinstance(getattr(arg0, "value", None), str) \
                else None
            if tail in ("get", "set") and chain[-2] == "conf":
                if literal is not None and literal not in options:
                    findings.append(Finding(
                        "registry", relpath, node.lineno, "conf-key",
                        f"conf.{tail}({literal!r}): key not in "
                        f"common/config.py's option table "
                        f"(KeyError at runtime)"))
            elif literal is not None and (
                    tail in _MUTATORS
                    or (tail == "set" and "perf" in chain[:-1])):
                # counter mutation — receiver must not be a conf
                if chain[-2] == "conf":
                    continue
                if literal not in counters:
                    findings.append(Finding(
                        "registry", relpath, node.lineno,
                        "perf-counter",
                        f".{tail}({literal!r}): counter never "
                        f"registered by any PerfCountersBuilder chain "
                        f"(KeyError inside the counter lock)"))
    return findings
