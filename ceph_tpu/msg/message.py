"""Message base class and type registry (src/msg/Message.h analog).

Every concrete message declares a TYPE id and HEAD_VERSION/COMPAT_VERSION and
implements encode_payload/decode_payload; the wire frame adds a fixed header
(type, versions, seq, payload length) and a crc32 trailer, standing where
ceph_msg_header/ceph_msg_footer stand (msg/Message.h, include/msgr.h).
"""

from __future__ import annotations

import struct
import zlib

from .encoding import Decoder, DecodeError, Encoder

_REGISTRY: dict[int, type] = {}

_HEADER = struct.Struct("<IHBBQ I")   # type, flags, ver, compat, seq, len
_FOOTER = struct.Struct("<I")         # crc32 of payload
#: header flag bit 0: the v1 trace extension (trace_id u64) follows
#: the fixed header — untraced frames are byte-identical to the
#: pre-tracing format, so archived corpora still decode/re-encode
_FLAG_TRACE = 0x1
_TRACE_EXT = struct.Struct("<Q")
#: header flag bit 1: the v2 SPAN trace extension
#: (trace_id u64, parent_span_id u64) — emitted only when the sender
#: carries a span parent (and, on wire stacks, only to peers that
#: negotiated FEATURE_TRACE_SPANS); senders without a span parent
#: keep emitting the v1 extension, so old peers keep decoding
_FLAG_TRACE_SPAN = 0x2
_TRACE_SPAN_EXT = struct.Struct("<QQ")


def register_message(cls):
    """Class decorator: adds the type to the catalog (the analog of the
    decode_message switch over 154 types, src/msg/Message.cc)."""
    t = cls.TYPE
    if t in _REGISTRY and _REGISTRY[t] is not cls:
        raise ValueError(f"message type {t} already registered "
                         f"({_REGISTRY[t].__name__})")
    _REGISTRY[t] = cls
    return cls


class Message:
    TYPE = 0
    HEAD_VERSION = 1
    COMPAT_VERSION = 1

    def __init__(self):
        self.seq = 0
        #: filled by the messenger on receive: the Connection it arrived on
        self.connection = None
        #: cross-daemon trace id (0 = untraced); rides the frame
        #: header extension and propagates through dispatch threads
        #: (common/tracing)
        self.trace_id = 0
        #: sender-side span this message descends from (0 = none):
        #: receivers parent their rx dispatch spans here, stitching
        #: the cross-daemon span tree
        self.parent_span_id = 0

    # subclasses implement:
    def encode_payload(self, enc: Encoder) -> None:
        raise NotImplementedError

    def decode_payload(self, dec: Decoder, version: int) -> None:
        raise NotImplementedError

    # -- framing --------------------------------------------------------------

    def encode(self) -> bytes:
        enc = Encoder()
        self.encode_payload(enc)
        payload = enc.tobytes()
        tid = getattr(self, "trace_id", 0)
        psid = getattr(self, "parent_span_id", 0)
        if tid and psid:
            flags = _FLAG_TRACE_SPAN
            ext = _TRACE_SPAN_EXT.pack(tid, psid)
        elif tid:
            flags = _FLAG_TRACE
            ext = _TRACE_EXT.pack(tid)
        else:
            flags = 0
            ext = b""
        header = _HEADER.pack(self.TYPE, flags, self.HEAD_VERSION,
                              self.COMPAT_VERSION, self.seq, len(payload))
        return header + ext + payload + _FOOTER.pack(zlib.crc32(payload))

    @staticmethod
    def decode(data: bytes) -> "Message":
        if len(data) < _HEADER.size + _FOOTER.size:
            raise DecodeError("short message frame")
        mtype, flags, ver, compat, seq, plen = _HEADER.unpack_from(data, 0)
        start = _HEADER.size
        trace_id = 0
        parent_span_id = 0
        if flags & _FLAG_TRACE_SPAN:
            if len(data) < start + _TRACE_SPAN_EXT.size:
                raise DecodeError("truncated span trace extension")
            trace_id, parent_span_id = \
                _TRACE_SPAN_EXT.unpack_from(data, start)
            start += _TRACE_SPAN_EXT.size
        elif flags & _FLAG_TRACE:
            if len(data) < start + _TRACE_EXT.size:
                raise DecodeError("truncated trace extension")
            (trace_id,) = _TRACE_EXT.unpack_from(data, start)
            start += _TRACE_EXT.size
        if len(data) < start + plen + _FOOTER.size:
            raise DecodeError("truncated payload")
        payload = data[start:start + plen]
        (crc,) = _FOOTER.unpack_from(data, start + plen)
        if zlib.crc32(payload) != crc:
            raise DecodeError(f"payload crc mismatch on type {mtype}")
        cls = _REGISTRY.get(mtype)
        if cls is None:
            raise DecodeError(f"unknown message type {mtype}")
        if compat > cls.HEAD_VERSION:
            raise DecodeError(
                f"message type {mtype} compat {compat} > understood "
                f"{cls.HEAD_VERSION}")
        msg = cls.__new__(cls)
        Message.__init__(msg)
        msg.seq = seq
        msg.trace_id = trace_id
        msg.parent_span_id = parent_span_id
        msg.decode_payload(Decoder(payload), ver)
        return msg

    def frame_size(self) -> int:
        return len(self.encode())

    def __repr__(self):
        return f"<{type(self).__name__} seq={self.seq}>"


def message_type_name(t: int) -> str:
    cls = _REGISTRY.get(t)
    return cls.__name__ if cls else f"unknown({t})"
