"""Authentication (src/auth/ analog): the cephx ticket protocol."""

from ceph_tpu.auth.cephx import (  # noqa: F401
    KeyServer, Ticket, TicketKeyring, derive_session_key,
    mint_ticket, validate_ticket)
