"""Device-mesh construction.

One function builds every mesh in the framework so axis naming stays consistent:
``dp`` (data/placement parallel) x ``ec`` (erasure-shard parallel).  On a v5e pod
slice the mesh should be laid out so ``ec`` rides the minor (fastest ICI) axis —
`mesh_utils.create_device_mesh` handles the physical layout when available.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def factor_devices(n: int, ec_max: int = 4, ec_divides: int | None = None) -> tuple[int, int]:
    """Split n devices into (dp, ec).

    Without ``ec_divides`` the split is pure data parallelism (ec=1): an
    ec axis only helps when the k+m chunk count is KNOWN to divide it —
    otherwise chunk rows split unevenly across the ec axis and shard_map
    callers fail on the ragged block.  (The old default picked the
    largest ec <= ec_max whenever it divided n, handing ec=4 meshes to
    callers that never promised any chunk-axis divisibility.)  With
    ``ec_divides`` (the k+m chunk count), ec is the largest divisor of n
    that is <= ec_max and divides it, so chunk rows split evenly; ec=1
    remains the fallback for awkward n.
    """
    if ec_divides is None:
        return n, 1
    best = 1
    for d in range(1, n + 1):
        if n % d or d > ec_max:
            continue
        if ec_divides % d:
            continue
        best = d
    return n // best, best


def make_mesh(n_devices: int | None = None, *, ec: int | None = None,
              ec_divides: int | None = None) -> Mesh:
    """Build a ("dp", "ec") mesh over the first n_devices jax devices."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if n > len(devices):
        raise ValueError(f"asked for {n} devices, only {len(devices)} present")
    if ec is None:
        dp, ec = factor_devices(n, ec_divides=ec_divides)
    else:
        if n % ec:
            raise ValueError(f"ec={ec} does not divide n={n}")
        dp = n // ec
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh((dp, ec), devices=devices[:n])
    except Exception:
        dev_array = np.array(devices[:n]).reshape(dp, ec)
    return Mesh(dev_array, axis_names=("dp", "ec"))


