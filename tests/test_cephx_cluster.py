"""Full-cluster cephx: per-entity keys everywhere, mon-granted tickets
on every data-path connection, and the VERDICT contract — `auth del
client.x` cuts exactly client.x's next access while the cluster keeps
running; a wrong key is rejected at the handshake."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="async", cephx=True).start()
    c.wait_for_osd_count(3)
    yield c
    c.stop()


def test_cluster_forms_and_io_works(cluster):
    admin = cluster.client()
    pool = cluster.create_pool(admin, pg_num=8, size=2)
    io = admin.open_ioctx(pool)
    io.write_full("obj", b"authenticated payload")
    assert io.read("obj") == b"authenticated payload"
    # every live mon connection carries a cephx identity
    mon = cluster.mon
    ents = {c.auth_entity for c in mon.msgr._conns.values()
            if c.auth_entity}
    assert any(e.startswith("osd.") for e in ents)


def test_provisioned_client_works_and_revocation_cuts_it(cluster):
    admin = cluster.client()
    pool = cluster.create_pool(admin, pg_num=8, size=2)
    key = cluster.provision_key("client.carol")
    carol = cluster.client_as("client.carol", key)
    io = carol.open_ioctx(pool)
    io.write_full("carols", b"hers")
    assert io.read("carols") == b"hers"

    # REVOKE carol; the cluster must keep serving everyone else
    rc, out = admin.mon_command({"prefix": "auth del",
                                 "entity": "client.carol"})
    assert rc == 0
    # carol's next ticket request is refused...
    rc, out = carol.mon_command({"prefix": "auth get-ticket",
                                 "service": "osd"})
    assert rc == -13, (rc, out)
    # ...and a FRESH mount with her (deleted) key dies at the mon
    with pytest.raises((OSError, TimeoutError)):
        cluster.client_as("client.carol", key, timeout=3.0)
    # while the admin and the cluster keep working
    io2 = admin.open_ioctx(pool)
    io2.write_full("after", b"still running")
    assert io2.read("after") == b"still running"


def test_wrong_key_rejected(cluster):
    with pytest.raises((OSError, TimeoutError)):
        cluster.client_as("client.admin", "bm90LXRoZS1rZXk=",
                          timeout=3.0)


def test_non_admin_cannot_admin(cluster):
    key = cluster.provision_key("client.lowpriv")
    low = cluster.client_as("client.lowpriv", key)
    for cmd in ({"prefix": "auth get-or-create", "entity": "client.x"},
                {"prefix": "auth del", "entity": "client.admin"},
                {"prefix": "auth ls"},
                {"prefix": "auth print-key",
                 "entity": "client.admin"}):
        rc, out = low.mon_command(cmd)
        assert rc == -13, (cmd, rc, out)
    # but harmless commands still work
    rc, _ = low.mon_command({"prefix": "status"})
    assert rc == 0
    # and it may not read service validation keys either
    rc, _ = low.mon_command({"prefix": "auth rotating",
                             "service": "osd"})
    assert rc == -13


def test_key_rotation_under_io(cluster):
    """Force service-key rotations; clients with fresh tickets keep
    working (old generations stay valid for LIVE_GENERATIONS)."""
    admin = cluster.client()
    pool = cluster.create_pool(admin, pg_num=8, size=2)
    io = admin.open_ioctx(pool)
    mon = cluster.mon
    mon._work_q.put(("rotate_keys",
                     lambda m: mon._keyserver(m.auth_db).rotate_now(
                         "osd") or True, None))
    time.sleep(0.5)
    io.write_full("rot", b"after one rotation")
    assert io.read("rot") == b"after one rotation"
    # the daemons refresh their rotating keys and keep validating
    for osd in cluster.osds.values():
        osd._refresh_rotating()
    io.write_full("rot2", b"after refresh")
    assert io.read("rot2") == b"after refresh"
