"""Thrasher — randomized fault injection under load
(qa/tasks/ceph_manager.py:98 Thrasher analog).

Drives a MiniCluster with a mixed replicated + EC workload while
randomly killing/reviving OSDs and marking them out/in.  The workload
tracks every ACKED write; during the storm reads may time out or return
stale-epoch errors (retried), but an acked object must NEVER read back
wrong bytes, and after the storm ends and the cluster heals, every
acked object must be present and correct — the durability contract the
reference earns with teuthology.
"""

from __future__ import annotations

import random
import threading
import time

from ceph_tpu.tools.vstart import MiniCluster


class Workload(threading.Thread):
    """Continuous write/read/delete mix against one pool."""

    def __init__(self, cluster: MiniCluster, pool: int, prefix: str,
                 rng: random.Random, payload_scale: int = 2000):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.pool = pool
        self.prefix = prefix
        self.rng = rng
        self.payload_scale = payload_scale
        self.acked: dict[str, bytes | None] = {}  # None = deleted
        #: full submission history per object (a timed-out write is
        #: unacked but MAY land — reads returning any value at or after
        #: the last acked submission are correct rados semantics)
        self.submitted: dict[str, list[bytes | None]] = {}
        self.acked_idx: dict[str, int] = {}
        self.corruptions: list[str] = []
        self.ops = 0
        self.errors = 0
        self._halt = threading.Event()  # Thread has a private _stop

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        client = self.cluster.client(timeout=6.0)
        io = client.open_ioctx(self.pool)
        try:
            while not self._halt.is_set():
                oid = f"{self.prefix}{self.rng.randrange(24)}"
                roll = self.rng.random()
                hist = self.submitted.setdefault(oid, [])
                try:
                    if roll < 0.5:
                        body = (f"{oid}-{self.ops}-".encode()
                                * self.rng.randrange(
                                    1, self.payload_scale))
                        hist.append(body)
                        io.write_full(oid, body)
                        self.acked[oid] = body   # acked => durable
                        self.acked_idx[oid] = len(hist) - 1
                    elif roll < 0.9:
                        if oid not in self.acked_idx:
                            continue
                        got = io.read(oid)
                        if not self._acceptable(oid, got):
                            self.corruptions.append(oid)
                    else:
                        if self.acked.get(oid) is None:
                            continue
                        hist.append(None)
                        io.remove(oid)
                        self.acked[oid] = None
                        self.acked_idx[oid] = len(hist) - 1
                    self.ops += 1
                except (TimeoutError, OSError):
                    # storms time ops out / error them; the op is not
                    # acked, so no durability claim attaches — but it
                    # may still land, hence the submission history
                    self.errors += 1
        finally:
            client.shutdown()

    def _acceptable(self, oid: str, got: bytes | None) -> bool:
        """True iff `got` is the last acked value or any LATER submitted
        one (unacked writes may land; going backwards past an acked
        write, or returning bytes never written, is the failure)."""
        idx = self.acked_idx.get(oid)
        if idx is None:
            return True
        for v in self.submitted[oid][idx:]:
            if got == v:
                return True
        return False

    def final_verify(self, client) -> list[str]:
        """After heal: every acked object at/after its acked state."""
        io = client.open_ioctx(self.pool)
        bad = []
        for oid, idx in sorted(self.acked_idx.items()):
            suffix = self.submitted[oid][idx:]
            if all(v is None for v in suffix):
                continue   # last acked state is deleted
            for attempt in range(3):
                got: bytes | None
                try:
                    got = io.read(oid)
                except TimeoutError:   # NB: subclass of OSError — first
                    time.sleep(1.0)
                    continue
                except OSError:
                    got = None     # absent: fine if a delete follows
                if self._acceptable(oid, got):
                    break
                time.sleep(1.0)
            else:
                bad.append(oid)
        return bad


class Thrasher:
    def __init__(self, cluster: MiniCluster, seed: int = 0,
                 min_up: int = 4, max_down: int = 1,
                 pools: dict[int, int] | None = None,
                 pg_num_max: int = 32, thrash_mons: bool = False):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.min_up = min_up
        self.max_down = max_down
        self.downed: list[int] = []
        self.outed: list[int] = []
        self.actions = 0
        #: pool -> current pg_num; the thrasher grows pg_num (PG split
        #: under load) and trails pgp_num behind it, like the reference
        #: Thrasher's thrash_pg_num (qa/tasks/ceph_manager.py)
        self.pg_nums: dict[int, int] = dict(pools or {})
        self.pgp_nums: dict[int, int] = dict(pools or {})
        self.pg_num_max = pg_num_max
        #: mon currently killed (at most one: quorum of 3 needs 2)
        self.thrash_mons = thrash_mons
        self.downed_mon: int | None = None

    def _mon_cmd(self, cmd: dict) -> None:
        client = self.cluster.clients[0]
        try:
            client.mon_command(cmd)
        except (TimeoutError, OSError):
            pass

    def step(self) -> str:
        roll = self.rng.random()
        up = [i for i in self.cluster.osds if i not in self.downed]
        if self.thrash_mons and len(self.cluster.mons) + (
                1 if self.downed_mon is not None else 0) >= 3:
            if self.downed_mon is not None and roll < 0.2:
                mon = self.downed_mon
                self.downed_mon = None
                if self.rng.random() < 0.5:
                    # mon REPLACE: revive with a WIPED store — the
                    # probe + store-sync path must rebuild it from the
                    # quorum (Monitor.cc sync_start)
                    try:
                        self.cluster.replace_mon(mon)
                        self.actions += 1
                        return f"replace mon.{mon} (wiped store)"
                    except (TimeoutError, RuntimeError):
                        self.downed_mon = mon   # retry next step
                        return f"replace mon.{mon} pending"
                self.cluster.run_mon(mon)
                self.actions += 1
                return f"revive mon.{mon}"
            if self.downed_mon is None and roll < 0.1:
                mon = self.rng.choice(sorted(self.cluster.mons))
                self.cluster.kill_mon(mon)
                self.downed_mon = mon
                self.actions += 1
                return f"kill mon.{mon}"
        if self.pg_nums and roll < 0.15:
            pool = self.rng.choice(sorted(self.pg_nums))
            if self.pgp_nums[pool] < self.pg_nums[pool]:
                self.pgp_nums[pool] = self.pg_nums[pool]
                self._mon_cmd({"prefix": "osd pool set", "pool": pool,
                               "var": "pgp_num",
                               "val": str(self.pgp_nums[pool])})
                self.actions += 1
                return f"grow pgp_num pool.{pool} -> {self.pgp_nums[pool]}"
            if self.pg_nums[pool] < self.pg_num_max:
                self.pg_nums[pool] *= 2
                self._mon_cmd({"prefix": "osd pool set", "pool": pool,
                               "var": "pg_num",
                               "val": str(self.pg_nums[pool])})
                self.actions += 1
                return f"grow pg_num pool.{pool} -> {self.pg_nums[pool]}"
            roll = 0.15 + self.rng.random() * 0.85
        if self.downed and (roll < 0.45 or len(self.downed)
                            >= self.max_down):
            osd = self.downed.pop(self.rng.randrange(len(self.downed)))
            self.cluster.run_osd(osd)
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
            self.actions += 1
            return f"revive osd.{osd}"
        if roll < 0.7 and len(up) > self.min_up \
                and len(self.downed) < self.max_down:
            osd = self.rng.choice(up)
            self.cluster.kill_osd(osd)
            self._mon_cmd({"prefix": "osd down", "id": str(osd)})
            self.downed.append(osd)
            self.actions += 1
            return f"kill osd.{osd}"
        if self.outed:
            osd = self.outed.pop()
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
            self.actions += 1
            return f"in osd.{osd}"
        candidates = [i for i in up if i not in self.outed]
        if candidates and len(up) - len(self.outed) > self.min_up:
            osd = self.rng.choice(candidates)
            self._mon_cmd({"prefix": "osd out", "id": str(osd)})
            self.outed.append(osd)
            self.actions += 1
            return f"out osd.{osd}"
        return "noop"

    def heal(self) -> None:
        """Revive everything and bring every OSD back in."""
        if self.downed_mon is not None:
            self.cluster.run_mon(self.downed_mon)
            self.downed_mon = None
        for osd in list(self.downed):
            self.cluster.run_osd(osd)
        self.downed.clear()
        for osd in list(self.outed):
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
        self.outed.clear()


def run_soak(duration: float = 25.0, seed: int = 7,
             n_osds: int = 6, base_path: str = "",
             ms_type: str = "loopback", n_mons: int = 1,
             thrash_mons: bool = False) -> dict:
    """The standalone soak: returns a result dict (the pytest wrapper
    asserts).  OSDs are filestore-backed: kill_osd is PROCESS death with
    the disk surviving, like the reference Thrasher — wiping stores
    faster than recovery completes would lose data in any storage
    system."""
    if not base_path:
        import tempfile
        base_path = tempfile.mkdtemp(prefix="thrash-")
    ici_t = None
    if ms_type == "ici":
        from ceph_tpu.msg.ici import IciTransport
        ici_t = IciTransport.instance()
    c = MiniCluster(n_osds=n_osds, ms_type=ms_type,
                    store_type="filestore", n_mons=n_mons,
                    base_path=base_path, heartbeats=True).start()
    try:
        c.wait_for_osd_count(n_osds)
        client = c.client(timeout=20.0)
        rep = c.create_pool(client, pg_num=8, size=3)
        ec = c.create_pool(client, pg_num=8, pool_type="erasure",
                           k=2, m=2)
        rng = random.Random(seed)
        w1 = Workload(c, rep, "r", random.Random(seed + 1))
        w2 = Workload(c, ec, "e", random.Random(seed + 2),
                      payload_scale=400)
        w1.start()
        w2.start()
        th = Thrasher(c, seed=seed, pools={rep: 8, ec: 8},
                      thrash_mons=thrash_mons)
        deadline = time.time() + duration
        log = []
        health_seen: set[str] = set()

        def sample_health() -> None:
            import json as _json
            try:
                rc, out = client.mon_command({"prefix": "health"})
                if rc == 0:
                    h = _json.loads(out)
                    health_seen.add(h["status"])
                    for ch in h["checks"]:
                        health_seen.add(ch["check"])
            except (TimeoutError, OSError, ValueError):
                pass

        while time.time() < deadline:
            log.append(th.step())
            sample_health()
            time.sleep(rng.uniform(0.5, 1.5))
        w1.stop()
        w2.stop()
        w1.join(timeout=30)
        w2.join(timeout=30)
        th.heal()
        c.wait_for_osd_count(n_osds, timeout=30)
        c.wait_for_epoch(c.mon.osdmap.epoch, timeout=30)
        time.sleep(3.0)   # recovery settles
        vclient = c.client(timeout=20.0)
        # health must transition: WARN during the storm, OK after heal
        import json as _json
        final_health = ""
        hdl = time.time() + 30
        while time.time() < hdl:
            try:
                rc, out = vclient.mon_command({"prefix": "health"})
            except (TimeoutError, OSError):
                time.sleep(0.5)
                continue
            if rc == 0:
                final_health = _json.loads(out)["status"]
                if final_health == "HEALTH_OK":
                    break
            time.sleep(0.5)
        bad1 = w1.final_verify(vclient)
        bad2 = w2.final_verify(vclient)
        ici_outstanding = None
        if ici_t is not None:
            # staged buffers must all be redeemed or reaped: wait out
            # the resend grace + loss TTL.  Keep the reading that hit
            # zero — re-sampling could catch a buffer a still-running
            # daemon staged a moment later
            hdl = time.time() + ici_t.TTL + ici_t.GRACE + 2
            while True:
                ici_outstanding = ici_t.outstanding()
                if ici_outstanding[0] == 0 or time.time() >= hdl:
                    break
                time.sleep(0.5)
        return {
            "actions": th.actions, "log": log,
            "health_seen": sorted(health_seen),
            "final_health": final_health,
            "ici_outstanding": ici_outstanding,
            "rep_ops": w1.ops, "ec_ops": w2.ops,
            "rep_errors": w1.errors, "ec_errors": w2.errors,
            "corruptions": w1.corruptions + w2.corruptions,
            "lost_rep": bad1, "lost_ec": bad2,
        }
    finally:
        c.stop()


if __name__ == "__main__":
    import json
    import sys
    res = run_soak(duration=float(sys.argv[1]) if len(sys.argv) > 1
                   else 25.0)
    print(json.dumps({k: v for k, v in res.items() if k != "log"}))
    sys.exit(1 if (res["corruptions"] or res["lost_rep"]
                   or res["lost_ec"]) else 0)
