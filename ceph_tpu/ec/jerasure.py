"""jerasure-family techniques.

The reference wraps the jerasure library (src/erasure-code/jerasure/; the SIMD
kernels live in empty submodules, so the math here is reimplemented from the
published constructions — Plank's jerasure 2.0 — not translated code).  Each
technique is a generator-matrix recipe; encode/decode lower to the shared
batched MXU kernel via the ErasureCode base.

Techniques (ErasureCodeJerasure.h:82-253):
  reed_sol_van    extended-Vandermonde distribution matrix (always MDS)
  reed_sol_r6_op  RAID-6: P = sum d_j, Q = sum 2^j d_j (m forced to 2)
  cauchy_orig     a[i][j] = 1/(i xor (m+j))
  cauchy_good     cauchy_orig normalized to minimize bitmatrix ones
                  (jerasure improve_coding_matrix semantics)

The bitmatrix schedule techniques (liberation, blaum_roth, liber8tion) are
registered in ceph_tpu.ec.bitmatrix.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf.tables import gf_inv, gf_mul, gf_pow

from .base import ErasureCode
from .registry import register


# ---------------------------------------------------------------------------
# matrix constructions
# ---------------------------------------------------------------------------

def extended_vandermonde_matrix(rows: int, cols: int) -> np.ndarray:
    """Extended Vandermonde: row 0 = e_0, row i = [1, i, i^2, ...],
    last row = e_{cols-1}.  Always MDS for rows <= 257 over GF(2^8)."""
    if rows > 257:
        raise ValueError(f"rows={rows} exceeds GF(2^8) extended-Vandermonde bound")
    vdm = np.zeros((rows, cols), dtype=np.uint8)
    vdm[0, 0] = 1
    for i in range(1, rows - 1):
        p = 1
        for j in range(cols):
            vdm[i, j] = p
            p = gf_mul(p, i)
    vdm[rows - 1, cols - 1] = 1
    return vdm


def big_vandermonde_distribution_matrix(rows: int, cols: int) -> np.ndarray:
    """Systematic form of the extended Vandermonde (jerasure
    reed_sol_big_vandermonde_distribution_matrix semantics): elementary column
    ops make the top cols x cols block the identity, then coding rows are
    scaled so their first column is all ones."""
    vdm = extended_vandermonde_matrix(rows, cols)
    for j in range(cols):
        if vdm[j, j] == 0:
            for j2 in range(j + 1, cols):
                if vdm[j, j2]:
                    vdm[:, [j, j2]] = vdm[:, [j2, j]]
                    break
            else:
                raise ValueError("extended Vandermonde unexpectedly singular")
        d = int(vdm[j, j])
        if d != 1:
            dinv = gf_inv(d)
            for i in range(rows):
                vdm[i, j] = gf_mul(int(vdm[i, j]), dinv)
        for j2 in range(cols):
            f = int(vdm[j, j2])
            if j2 != j and f:
                for i in range(rows):
                    vdm[i, j2] ^= gf_mul(f, int(vdm[i, j]))
    for i in range(cols, rows):
        d = int(vdm[i, 0])
        if d and d != 1:
            dinv = gf_inv(d)
            for j in range(cols):
                vdm[i, j] = gf_mul(int(vdm[i, j]), dinv)
    return vdm


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """RAID-6 generator: parity row of ones, Q row of 2^j (jerasure
    reed_sol_r6_coding_matrix semantics)."""
    gen = np.zeros((k + 2, k), dtype=np.uint8)
    gen[:k, :k] = np.eye(k, dtype=np.uint8)
    gen[k, :] = 1
    for j in range(k):
        gen[k + 1, j] = gf_pow(2, j)
    return gen


def cauchy_original_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_original_coding_matrix: a[i][j] = 1/(i xor (m+j))."""
    if k + m > 256:
        raise ValueError(f"k+m={k + m} exceeds GF(2^8) field size")
    gen = np.zeros((k + m, k), dtype=np.uint8)
    gen[:k, :k] = np.eye(k, dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            gen[k + i, j] = gf_inv(i ^ (m + j))
    return gen


def _bitmatrix_ones(e: int) -> int:
    """Ones in the 8x8 GF(2) bitmatrix of multiply-by-e: the XOR cost the
    improvement heuristic minimizes (jerasure cauchy.c)."""
    return sum(bin(gf_mul(e, 1 << b)).count("1") for b in range(8))


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """cauchy_orig normalized (jerasure improve_coding_matrix semantics):
    scale columns so coding row 0 is all ones, then scale each later row by
    the row element whose division minimizes total bitmatrix ones."""
    gen = cauchy_original_matrix(k, m)
    coding = gen[k:]
    for j in range(k):
        e = int(coding[0, j])
        if e != 1:
            einv = gf_inv(e)
            for i in range(m):
                coding[i, j] = gf_mul(int(coding[i, j]), einv)
    for i in range(1, m):
        row = [int(v) for v in coding[i]]
        best_row, best_cost = row, sum(_bitmatrix_ones(v) for v in row)
        for div in row:
            if div in (0, 1):
                continue
            dinv = gf_inv(div)
            cand = [gf_mul(v, dinv) for v in row]
            cost = sum(_bitmatrix_ones(v) for v in cand)
            if cost < best_cost:
                best_row, best_cost = cand, cost
        coding[i] = best_row
    return gen


# ---------------------------------------------------------------------------
# plugin classes
# ---------------------------------------------------------------------------

class ErasureCodeJerasure(ErasureCode):
    """Base for jerasure techniques; dispatches on profile technique=
    (ErasureCodeJerasure.cc factory behaviour).  Defaults k=7 m=3 w=8."""

    TECHNIQUE = ""

    def parse(self, profile):
        super().parse(profile)
        self.technique = profile.get("technique", self.TECHNIQUE)
        w = self.to_int("w", profile, 8)
        if w != 8:
            raise ValueError(
                f"w={w}: only w=8 is supported (GF(2^8) device kernels); the "
                f"reference default is also 8")
        self.w = w


class ReedSolomonVandermonde(ErasureCodeJerasure):
    TECHNIQUE = "reed_sol_van"

    def _build_generator(self):
        return big_vandermonde_distribution_matrix(self.k + self.m, self.k)


class ReedSolomonR6(ErasureCodeJerasure):
    TECHNIQUE = "reed_sol_r6_op"

    def parse(self, profile):
        super().parse(profile)
        self.m = 2  # RAID-6: m is forced to 2 (ErasureCodeJerasure.h:112)

    def _build_generator(self):
        return reed_sol_r6_matrix(self.k)


class CauchyOrig(ErasureCodeJerasure):
    TECHNIQUE = "cauchy_orig"

    def _build_generator(self):
        return cauchy_original_matrix(self.k, self.m)


class CauchyGood(ErasureCodeJerasure):
    TECHNIQUE = "cauchy_good"

    def _build_generator(self):
        return cauchy_good_matrix(self.k, self.m)


_TECHNIQUES = {
    "reed_sol_van": ReedSolomonVandermonde,
    "reed_sol_r6_op": ReedSolomonR6,
    "cauchy_orig": CauchyOrig,
    "cauchy_good": CauchyGood,
}


def _factory(profile):
    technique = profile.get("technique", "reed_sol_van")
    try:
        from . import bitmatrix
        cls = {**_TECHNIQUES, **bitmatrix.TECHNIQUES}[technique]
    except KeyError:
        raise ValueError(
            f"jerasure technique {technique!r} unknown; known: "
            f"{sorted(_TECHNIQUES)} + bitmatrix techniques")
    return cls()


register("jerasure", _factory)
