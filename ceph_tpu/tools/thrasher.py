"""Thrasher — randomized fault injection under load
(qa/tasks/ceph_manager.py:98 Thrasher analog).

Drives a MiniCluster with a mixed replicated + EC workload while
randomly killing/reviving OSDs and marking them out/in.  The workload
tracks every ACKED write; during the storm reads may time out or return
stale-epoch errors (retried), but an acked object must NEVER read back
wrong bytes, and after the storm ends and the cluster heals, every
acked object must be present and correct — the durability contract the
reference earns with teuthology.
"""

from __future__ import annotations

import random
import threading
import time

from ceph_tpu.tools.vstart import MiniCluster


class DeviceChaos:
    """Device-runtime chaos: fires failpoints at the dispatch engine's
    device boundaries (common/failpoint.py) while the mixed workload
    runs — the accelerator-side analog of killing OSDs.

    A storm keeps every kernel channel's launch failing at
    ``BASE_RATE`` (the >=10%% chaos-gate floor: transient faults that
    the bounded retry ladder must absorb), and each step may also
    declare a HARD OUTAGE on one channel (mode ``always`` — the
    breaker must open and the bit-exact host oracle must carry the
    channel), heal one, arm the device_put / block_until_ready
    boundaries, or kill an engine run-loop outright (supervision must
    revive it and re-fan its in-flight batches).  ``clear()`` disarms
    everything; afterwards every breaker must re-close via the
    background probes — the reconvergence half of the durability
    contract."""

    #: the kernel channels the chaos gate names (encode, decode, fused
    #: placement ladder, objectstore write-time digests); crush and
    #: scrub channels ride the same machinery
    CHANNELS = ("ec_encode", "ec_decode", "pg_finish",
                "bluestore_data")
    BASE_RATE = 0.15

    def __init__(self, rng: random.Random):
        self.rng = rng
        self.actions = 0
        #: channels currently under a hard outage (breaker expected
        #: open while this is non-empty)
        self.outages: set[str] = set()

    def start(self) -> None:
        from ceph_tpu.common import failpoint
        failpoint.seed(self.rng.randrange(1 << 31))
        for ch in self.CHANNELS:
            failpoint.set(f"dispatch.launch:{ch}",
                          f"prob:{self.BASE_RATE}")

    def step(self) -> str:
        from ceph_tpu.common import failpoint
        roll = self.rng.random()
        ch = self.rng.choice(self.CHANNELS)
        self.actions += 1
        if roll < 0.22:
            failpoint.set(f"dispatch.launch:{ch}", "always")
            self.outages.add(ch)
            return f"chaos outage {ch}"
        if roll < 0.5:
            failpoint.set(f"dispatch.launch:{ch}",
                          f"prob:{self.BASE_RATE}")
            self.outages.discard(ch)
            return f"chaos heal {ch}"
        if roll < 0.68:
            site = self.rng.choice(("dispatch.device_put",
                                    "dispatch.block_until_ready"))
            failpoint.set(f"{site}:{ch}", f"prob:{self.BASE_RATE}")
            return f"chaos arm {site}:{ch}"
        if roll < 0.82:
            role = self.rng.choice(("dispatch", "complete"))
            failpoint.set(f"dispatch.{role}_thread_death", "oneshot")
            return f"chaos kill {role} run-loop"
        return "chaos noop"

    def clear(self) -> None:
        from ceph_tpu.common import failpoint
        failpoint.clear()
        self.outages.clear()

    @staticmethod
    def await_reconverged(timeout: float = 20.0,
                          cluster=None) -> tuple[bool, dict]:
        """After clear(): wait for every channel breaker to re-close
        via the background probes.  When a MiniCluster is given, the
        gate additionally reads each live engine's OWN breaker map:
        the process-global stats sink is shared by every in-process
        daemon and keyed by channel only, so daemon B's re-close there
        is last-writer-wins over daemon A's still-open breaker — the
        per-engine maps are the ground truth the acceptance gate
        needs.  Returns (reconverged, final fault digest)."""
        from ceph_tpu.ops import telemetry

        def engine_states() -> list[int]:
            if cluster is None:
                return []
            states: list[int] = []
            for osd in list(cluster.osds.values()):
                ctx = getattr(osd, "ctx", None)
                # private attrs on purpose: the public accessors
                # lazily BUILD an engine, and a daemon that never
                # dispatched has no breakers to wait on
                for eng in (getattr(ctx, "_dispatch", None),
                            getattr(ctx, "_decode_dispatch", None)):
                    if eng is not None:
                        states.extend(eng.breaker_states().values())
            return states

        deadline = time.time() + timeout
        digest: dict = {}
        while time.time() < deadline:
            digest = telemetry.fault_digest()
            if cluster is not None:
                # live-engine ground truth ONLY: a daemon killed
                # mid-outage leaves its OPEN as the sink's last write
                # for that channel forever (its engine is stopped and
                # can never re-close), which would fail the gate on a
                # healthy cluster
                states = engine_states()
            else:
                states = [st for d in digest.values()
                          for st in d.get("breaker_states", {}).values()]
            if all(st == telemetry.BREAKER_CLOSED for st in states):
                return True, digest
            time.sleep(0.25)
        return False, digest


class ScrubStorm(threading.Thread):
    """``--scrub-storm``: continuous deep scrub + on-disk bit-flip
    injection while the OSD-kill/chaos storm runs.

    A dedicated integrity pool holds a fixed object population with
    KNOWN payloads (the workload pools keep overwriting theirs, which
    would make "repaired back to truth" unverifiable).  The storm
    loop alternates full ``scrub_all_pgs`` sweeps on every live OSD
    with bit flips written straight into a random copy's object store
    — version attrs untouched, so log-based recovery cannot see the
    damage and only integrity checking can.  Gate (``verify()``):
    after heal, every injected corruption was detected and repaired —
    every live copy of every integrity object reads back as its
    written payload — with the cluster scrub ledger alongside.  The
    ledger's ``repair_unverified`` may be transiently non-zero during
    a kill storm (the repair target died mid-verification); the gate
    is final convergence (``unrepaired`` empty), not a zero there."""

    def __init__(self, cluster: MiniCluster, pool: int,
                 rng: random.Random, n_objects: int = 8):
        super().__init__(daemon=True, name="scrub-storm")
        self.cluster = cluster
        self.pool = pool
        self.rng = rng
        self._halt = threading.Event()
        self.payloads: dict[str, bytes] = {}
        self.injected: list[tuple[int, str, str]] = []
        self.sweeps = 0
        self.sweep_errors = 0
        # generous timeout + per-object retries: with --chaos the
        # first writes pay cold jit compiles and may time out once
        client = cluster.client(timeout=60.0)
        try:
            io = client.open_ioctx(pool)
            for i in range(n_objects):
                body = f"integrity-{i}-".encode() * 64
                for _attempt in range(3):
                    try:
                        io.write_full(f"int{i}", body)
                    except (TimeoutError, OSError):
                        time.sleep(1.0)
                        continue
                    self.payloads[f"int{i}"] = body
                    break
        finally:
            client.shutdown()

    def stop(self) -> None:
        self._halt.set()

    def _placement(self, oid: str):
        from ceph_tpu.client.rados import ceph_str_hash_rjenkins
        from ceph_tpu.osd.osdmap import pg_to_pgid
        m = self.cluster.mon.osdmap
        pool = m.pools.get(self.pool)
        if pool is None:
            return None, []
        pg = pg_to_pgid(ceph_str_hash_rjenkins(oid), pool.pg_num)
        up, _primary, _a, _ap = m.pg_to_up_acting_osds(self.pool, pg)
        return pg, [o for o in up if o >= 0]

    def _flip_one(self) -> str | None:
        """Flip one bit of one copy, store-direct (silent corruption:
        no log entry, no version change — scrub's problem to find)."""
        from ceph_tpu.objectstore import Transaction
        if not self.payloads:
            return None     # every seed write failed: nothing to flip
        oid = self.rng.choice(sorted(self.payloads))
        pg, up = self._placement(oid)
        cands = [o for o in up if o in self.cluster.osds]
        if pg is None or not cands:
            return None
        victim = self.rng.choice(cands)
        osd = self.cluster.osds.get(victim)
        if osd is None:
            return None
        cid = f"{self.pool}.{pg}"
        try:
            data = osd.store.read(cid, oid)
            if not data:
                return None
            off = self.rng.randrange(len(data))
            osd.store.apply_transaction(Transaction().write(
                cid, oid, off, bytes([data[off] ^ 0x40])))
        except Exception:
            return None      # victim died under us: the storm goes on
        self.injected.append((victim, cid, oid))
        return f"scrub-storm flip {oid} on osd.{victim}"

    def _sweep_all(self, ignore_halt: bool = False) -> None:
        for _i, osd in sorted(self.cluster.osds.items()):
            if self._halt.is_set() and not ignore_halt:
                return
            try:
                osd.scrub_all_pgs(timeout=60.0)
                self.sweeps += 1
            except Exception:
                self.sweep_errors += 1

    def run(self) -> None:
        while not self._halt.is_set():
            if self.rng.random() < 0.7:
                self._flip_one()
            self._sweep_all()
            self._halt.wait(0.25)

    def _bad_copies(self) -> list[tuple[int, str, str]]:
        bad = []
        for oid, body in sorted(self.payloads.items()):
            pg, up = self._placement(oid)
            if pg is None:
                continue
            cid = f"{self.pool}.{pg}"
            for o in up:
                osd = self.cluster.osds.get(o)
                if osd is None:
                    continue
                try:
                    data = osd.store.read(cid, oid)
                except Exception:
                    bad.append((o, oid, "unreadable"))
                    continue
                if data != body:
                    bad.append((o, oid, "mismatch"))
        return bad

    def verify(self, timeout: float = 90.0) -> dict:
        """Post-heal gate: keep sweeping until every live copy of
        every integrity object matches its written payload (injected
        corruption detected AND repaired), or the deadline."""
        from ceph_tpu.ops import telemetry
        end = time.time() + timeout
        bad = self._bad_copies()
        while bad and time.time() < end:
            self._sweep_all(ignore_halt=True)
            time.sleep(0.5)
            bad = self._bad_copies()
        return {"objects": len(self.payloads),
                "injected": len(self.injected),
                "sweeps": self.sweeps,
                "sweep_errors": self.sweep_errors,
                "unrepaired": [f"osd.{o}:{oid}:{why}"
                               for o, oid, why in bad],
                "ledger": telemetry.scrub_summary()}


class Workload(threading.Thread):
    """Continuous write/read/delete mix against one pool."""

    def __init__(self, cluster: MiniCluster, pool: int, prefix: str,
                 rng: random.Random, payload_scale: int = 2000):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.pool = pool
        self.prefix = prefix
        self.rng = rng
        self.payload_scale = payload_scale
        self.acked: dict[str, bytes | None] = {}  # None = deleted
        #: full submission history per object (a timed-out write is
        #: unacked but MAY land — reads returning any value at or after
        #: the last acked submission are correct rados semantics)
        self.submitted: dict[str, list[bytes | None]] = {}
        self.acked_idx: dict[str, int] = {}
        self.corruptions: list[str] = []
        self.ops = 0
        self.errors = 0
        self._halt = threading.Event()  # Thread has a private _stop

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        client = self.cluster.client(timeout=6.0)
        io = client.open_ioctx(self.pool)
        try:
            while not self._halt.is_set():
                oid = f"{self.prefix}{self.rng.randrange(24)}"
                roll = self.rng.random()
                hist = self.submitted.setdefault(oid, [])
                try:
                    if roll < 0.5:
                        body = (f"{oid}-{self.ops}-".encode()
                                * self.rng.randrange(
                                    1, self.payload_scale))
                        hist.append(body)
                        io.write_full(oid, body)
                        self.acked[oid] = body   # acked => durable
                        self.acked_idx[oid] = len(hist) - 1
                    elif roll < 0.9:
                        if oid not in self.acked_idx:
                            continue
                        got = io.read(oid)
                        if not self._acceptable(oid, got):
                            self.corruptions.append(oid)
                    else:
                        if self.acked.get(oid) is None:
                            continue
                        hist.append(None)
                        io.remove(oid)
                        self.acked[oid] = None
                        self.acked_idx[oid] = len(hist) - 1
                    self.ops += 1
                except (TimeoutError, OSError):
                    # storms time ops out / error them; the op is not
                    # acked, so no durability claim attaches — but it
                    # may still land, hence the submission history
                    self.errors += 1
        finally:
            client.shutdown()

    def _acceptable(self, oid: str, got: bytes | None) -> bool:
        """True iff `got` is the last acked value or any LATER submitted
        one (unacked writes may land; going backwards past an acked
        write, or returning bytes never written, is the failure)."""
        idx = self.acked_idx.get(oid)
        if idx is None:
            return True
        for v in self.submitted[oid][idx:]:
            if got == v:
                return True
        return False

    def final_verify(self, client) -> list[str]:
        """After heal: every acked object at/after its acked state."""
        io = client.open_ioctx(self.pool)
        bad = []
        for oid, idx in sorted(self.acked_idx.items()):
            suffix = self.submitted[oid][idx:]
            if all(v is None for v in suffix):
                continue   # last acked state is deleted
            for attempt in range(3):
                got: bytes | None
                try:
                    got = io.read(oid)
                except TimeoutError:   # NB: subclass of OSError — first
                    time.sleep(1.0)
                    continue
                except OSError:
                    got = None     # absent: fine if a delete follows
                if self._acceptable(oid, got):
                    break
                time.sleep(1.0)
            else:
                bad.append(oid)
        return bad


class Thrasher:
    def __init__(self, cluster: MiniCluster, seed: int = 0,
                 min_up: int = 4, max_down: int = 1,
                 pools: dict[int, int] | None = None,
                 pg_num_max: int = 32, thrash_mons: bool = False):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.min_up = min_up
        self.max_down = max_down
        self.downed: list[int] = []
        self.outed: list[int] = []
        self.actions = 0
        #: pool -> current pg_num; the thrasher grows pg_num (PG split
        #: under load) and trails pgp_num behind it, like the reference
        #: Thrasher's thrash_pg_num (qa/tasks/ceph_manager.py)
        self.pg_nums: dict[int, int] = dict(pools or {})
        self.pgp_nums: dict[int, int] = dict(pools or {})
        self.pg_num_max = pg_num_max
        #: mon currently killed (at most one: quorum of 3 needs 2)
        self.thrash_mons = thrash_mons
        self.downed_mon: int | None = None

    def _mon_cmd(self, cmd: dict) -> None:
        client = self.cluster.clients[0]
        try:
            client.mon_command(cmd)
        except (TimeoutError, OSError):
            pass

    def step(self) -> str:
        roll = self.rng.random()
        up = [i for i in self.cluster.osds if i not in self.downed]
        if self.thrash_mons and len(self.cluster.mons) + (
                1 if self.downed_mon is not None else 0) >= 3:
            if self.downed_mon is not None and roll < 0.2:
                mon = self.downed_mon
                self.downed_mon = None
                if self.rng.random() < 0.5:
                    # mon REPLACE: revive with a WIPED store — the
                    # probe + store-sync path must rebuild it from the
                    # quorum (Monitor.cc sync_start)
                    try:
                        self.cluster.replace_mon(mon)
                        self.actions += 1
                        return f"replace mon.{mon} (wiped store)"
                    except (TimeoutError, RuntimeError):
                        self.downed_mon = mon   # retry next step
                        return f"replace mon.{mon} pending"
                self.cluster.run_mon(mon)
                self.actions += 1
                return f"revive mon.{mon}"
            if self.downed_mon is None and roll < 0.1:
                mon = self.rng.choice(sorted(self.cluster.mons))
                self.cluster.kill_mon(mon)
                self.downed_mon = mon
                self.actions += 1
                return f"kill mon.{mon}"
        if self.pg_nums and roll < 0.15:
            pool = self.rng.choice(sorted(self.pg_nums))
            if self.pgp_nums[pool] < self.pg_nums[pool]:
                self.pgp_nums[pool] = self.pg_nums[pool]
                self._mon_cmd({"prefix": "osd pool set", "pool": pool,
                               "var": "pgp_num",
                               "val": str(self.pgp_nums[pool])})
                self.actions += 1
                return f"grow pgp_num pool.{pool} -> {self.pgp_nums[pool]}"
            if self.pg_nums[pool] < self.pg_num_max:
                self.pg_nums[pool] *= 2
                self._mon_cmd({"prefix": "osd pool set", "pool": pool,
                               "var": "pg_num",
                               "val": str(self.pg_nums[pool])})
                self.actions += 1
                return f"grow pg_num pool.{pool} -> {self.pg_nums[pool]}"
            roll = 0.15 + self.rng.random() * 0.85
        if self.downed and (roll < 0.45 or len(self.downed)
                            >= self.max_down):
            osd = self.downed.pop(self.rng.randrange(len(self.downed)))
            self.cluster.run_osd(osd)
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
            self.actions += 1
            return f"revive osd.{osd}"
        if roll < 0.7 and len(up) > self.min_up \
                and len(self.downed) < self.max_down:
            osd = self.rng.choice(up)
            self.cluster.kill_osd(osd)
            self._mon_cmd({"prefix": "osd down", "id": str(osd)})
            self.downed.append(osd)
            self.actions += 1
            return f"kill osd.{osd}"
        if self.outed:
            osd = self.outed.pop()
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
            self.actions += 1
            return f"in osd.{osd}"
        candidates = [i for i in up if i not in self.outed]
        if candidates and len(up) - len(self.outed) > self.min_up:
            osd = self.rng.choice(candidates)
            self._mon_cmd({"prefix": "osd out", "id": str(osd)})
            self.outed.append(osd)
            self.actions += 1
            return f"out osd.{osd}"
        return "noop"

    def heal(self) -> None:
        """Revive everything and bring every OSD back in."""
        if self.downed_mon is not None:
            self.cluster.run_mon(self.downed_mon)
            self.downed_mon = None
        for osd in list(self.downed):
            self.cluster.run_osd(osd)
        self.downed.clear()
        for osd in list(self.outed):
            self._mon_cmd({"prefix": "osd in", "id": str(osd)})
        self.outed.clear()


def run_soak(duration: float = 25.0, seed: int = 7,
             n_osds: int = 6, base_path: str = "",
             ms_type: str = "loopback", n_mons: int = 1,
             thrash_mons: bool = False,
             device_chaos: bool = False,
             scrub_storm: bool = False) -> dict:
    """The standalone soak: returns a result dict (the pytest wrapper
    asserts).  OSDs are filestore-backed: kill_osd is PROCESS death with
    the disk surviving, like the reference Thrasher — wiping stores
    faster than recovery completes would lose data in any storage
    system.

    ``device_chaos=True`` additionally storms the DEVICE runtime
    (DeviceChaos): failpoints fire at the dispatch engines' device
    boundaries on every kernel channel while OSDs die around them.
    The acked-object durability contract is unchanged — a device fault
    may slow an op (retry ladder) or degrade it host-side (breaker +
    bit-exact oracle) but never corrupt it — and after the storm every
    breaker must re-close (reconvergence to the device path).

    ``scrub_storm=True`` runs ScrubStorm alongside: continuous deep
    scrub of every PG plus on-disk bit-flip injection into a dedicated
    integrity pool while OSDs die and (with device_chaos) the digest
    channel itself degrades.  Gate: every injected corruption detected
    and repaired, zero acked corruption."""
    if not base_path:
        import tempfile
        base_path = tempfile.mkdtemp(prefix="thrash-")
    ici_t = None
    if ms_type == "ici":
        from ceph_tpu.msg.ici import IciTransport
        ici_t = IciTransport.instance()
    chaos = None
    storm = None
    osd_conf = {}
    if device_chaos:
        # toy pools sit under the osdmap_mapping_min_pgs floor and
        # would never exercise the fused-ladder device channel: lower
        # it so pg_finish traffic is real during the storm
        osd_conf["osdmap_mapping_min_pgs"] = 1
    if scrub_storm:
        # sweeps must not park a whole chunk timeout behind every
        # killed replica: short gathers + verification windows keep
        # the storm's scrub duty cycle high
        osd_conf.setdefault("osd_scrub_chunk_timeout", 4.0)
        osd_conf.setdefault("osd_scrub_verify_timeout", 8.0)
    # toy commits stage a handful of blocks each; drop the batch
    # floors so the bluestore_data channel is live for every storm
    osd_conf.setdefault("bluestore_batched_csum_min", 1)
    osd_conf.setdefault("bluestore_batched_read_min", 1)
    # bluestore-backed soak: kill_osd is a clean shutdown (the store
    # unmounts), so the disk-backed store is safe here AND the
    # bluestore_data channel sees real commit traffic all storm long
    c = MiniCluster(n_osds=n_osds, ms_type=ms_type,
                    store_type="bluestore", n_mons=n_mons,
                    base_path=base_path, heartbeats=True,
                    osd_conf=osd_conf).start()
    try:
        c.wait_for_osd_count(n_osds)
        client = c.client(timeout=20.0)
        # chaos mode runs the fused ladder on these toy pools
        # (min_pgs=1 above): on a COLD process the first map epoch per
        # pool pays the ladder's jit trace+compile inside _handle_map
        # — tens of seconds on a 1-core host — so the epoch wait must
        # be compile-sized or a cold standalone run flakes at setup
        ept = 90.0 if device_chaos else 10.0
        rep = c.create_pool(client, pg_num=8, size=3,
                            epoch_timeout=ept)
        ec = c.create_pool(client, pg_num=8, pool_type="erasure",
                           k=2, m=2, epoch_timeout=ept)
        rng = random.Random(seed)
        w1 = Workload(c, rep, "r", random.Random(seed + 1))
        w2 = Workload(c, ec, "e", random.Random(seed + 2),
                      payload_scale=400)
        w1.start()
        w2.start()
        th = Thrasher(c, seed=seed, pools={rep: 8, ec: 8},
                      thrash_mons=thrash_mons)
        if scrub_storm:
            spool = c.create_pool(client, pg_num=8, size=3,
                                  epoch_timeout=ept)
            storm = ScrubStorm(c, spool, random.Random(seed + 4))
            storm.start()
        if device_chaos:
            # fault-free warmup first: on a cold process the first ops
            # PAY the jit compiles (encode kernel, mapper, ladder);
            # arming failpoints before any op has ever succeeded would
            # storm an empty pipeline and measure nothing
            wdl = time.time() + 8.0
            while w1.ops + w2.ops < 6 and time.time() < wdl:
                time.sleep(0.25)
            chaos = DeviceChaos(random.Random(seed + 3))
            chaos.start()
        deadline = time.time() + duration
        log = []
        health_seen: set[str] = set()

        def sample_health() -> None:
            import json as _json
            try:
                rc, out = client.mon_command({"prefix": "health"})
                if rc == 0:
                    h = _json.loads(out)
                    health_seen.add(h["status"])
                    for ch in h["checks"]:
                        health_seen.add(ch["check"])
            except (TimeoutError, OSError, ValueError):
                pass

        while time.time() < deadline:
            log.append(th.step())
            if chaos is not None:
                log.append(chaos.step())
            sample_health()
            time.sleep(rng.uniform(0.5, 1.5))
        reconverged = None
        fault_digest: dict = {}
        if chaos is not None:
            # faults clear BEFORE the heal/verify phase: the storm is
            # over, the probes must re-close every breaker and traffic
            # must return to the device path while recovery drains
            chaos.clear()
            reconverged, fault_digest = chaos.await_reconverged(cluster=c)
        w1.stop()
        w2.stop()
        if storm is not None:
            storm.stop()
        w1.join(timeout=30)
        w2.join(timeout=30)
        if storm is not None:
            storm.join(timeout=60)
        th.heal()
        c.wait_for_osd_count(n_osds, timeout=30)
        c.wait_for_epoch(c.mon.osdmap.epoch, timeout=30)
        time.sleep(3.0)   # recovery settles
        scrub_result = storm.verify() if storm is not None else None
        vclient = c.client(timeout=20.0)
        # health must transition: WARN during the storm, OK after heal
        import json as _json
        final_health = ""
        hdl = time.time() + 30
        while time.time() < hdl:
            try:
                rc, out = vclient.mon_command({"prefix": "health"})
            except (TimeoutError, OSError):
                time.sleep(0.5)
                continue
            if rc == 0:
                final_health = _json.loads(out)["status"]
                if final_health == "HEALTH_OK":
                    break
            time.sleep(0.5)
        bad1 = w1.final_verify(vclient)
        bad2 = w2.final_verify(vclient)
        ici_outstanding = None
        if ici_t is not None:
            # staged buffers must all be redeemed or reaped: wait out
            # the resend grace + loss TTL.  Keep the reading that hit
            # zero — re-sampling could catch a buffer a still-running
            # daemon staged a moment later
            hdl = time.time() + ici_t.TTL + ici_t.GRACE + 2
            while True:
                ici_outstanding = ici_t.outstanding()
                if ici_outstanding[0] == 0 or time.time() >= hdl:
                    break
                time.sleep(0.5)
        return {
            "actions": th.actions, "log": log,
            "health_seen": sorted(health_seen),
            "final_health": final_health,
            "ici_outstanding": ici_outstanding,
            "rep_ops": w1.ops, "ec_ops": w2.ops,
            "rep_errors": w1.errors, "ec_errors": w2.errors,
            "corruptions": w1.corruptions + w2.corruptions,
            "lost_rep": bad1, "lost_ec": bad2,
            "chaos_actions": chaos.actions if chaos else 0,
            "breakers_reconverged": reconverged,
            "fault_digest": fault_digest,
            "scrub_storm": scrub_result,
        }
    finally:
        if chaos is not None:
            chaos.clear()   # failpoints are process-global: a failed
            # soak must never leave them armed for the next test
        if storm is not None:
            storm.stop()
        c.stop()


if __name__ == "__main__":
    import json
    import sys
    flags = ("--chaos", "--scrub-storm")
    args = [a for a in sys.argv[1:] if a not in flags]
    res = run_soak(duration=float(args[0]) if args else 25.0,
                   device_chaos="--chaos" in sys.argv,
                   scrub_storm="--scrub-storm" in sys.argv)
    print(json.dumps({k: v for k, v in res.items() if k != "log"}))
    sres = res.get("scrub_storm") or {}
    bad = (res["corruptions"] or res["lost_rep"] or res["lost_ec"]
           or res["breakers_reconverged"] is False
           or bool(sres.get("unrepaired"))
           # a storm that never seeded its integrity pool proved
           # nothing — the gate must not pass vacuously
           or (res.get("scrub_storm") is not None
               and sres.get("objects", 0) == 0))
    sys.exit(1 if bad else 0)
