"""Cross-daemon trace spans (tracing/oprequest.tp + zipkin_trace.h
analogs): a trace id stamped on the client's op rides the message
frame, every daemon the op fans out to records span events, OpTracker
events join the trace, and the admin-socket dump stitches one
client → primary → shard timeline."""

from __future__ import annotations

import pytest

from ceph_tpu.common import tracing
from ceph_tpu.msg.message import Message
from ceph_tpu.messages import MOSDOp
from ceph_tpu.tools.vstart import MiniCluster


def test_frame_carries_trace_extension():
    m = MOSDOp(client_id=7, tid=1, oid="traced")
    m.trace_id = 0xDEADBEEF
    back = Message.decode(m.encode())
    assert back.trace_id == 0xDEADBEEF
    # untraced frames are byte-identical to the pre-tracing format
    plain = MOSDOp(client_id=7, tid=1, oid="traced")
    assert Message.decode(plain.encode()).trace_id == 0


def test_ec_write_reconstructs_three_daemon_trace():
    c = MiniCluster(n_osds=4, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(4)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=1, pool_type="erasure",
                             k=2, m=1)
        io = client.open_ioctx(pool)
        io.write_full("warm", b"w" * 4096)     # peering settled

        with tracing.trace_ctx() as tid:
            io.write_full("traced-obj", b"T" * 8192)

        rows = tracing.dump(tid)
        assert rows, "no span events recorded"
        daemons = {r["daemon"] for r in rows}
        # ONE write's trace spans the client and at least k+m OSDs
        assert any(d.startswith("client.") for d in daemons), daemons
        osds = {d for d in daemons if d.startswith("osd.")}
        assert len(osds) >= 3, daemons
        events = [r["event"] for r in rows]
        # the op itself, the EC shard fan-out, and the replies all join
        assert any("rx MOSDOp" in e for e in events), events
        assert any("MOSDECSubOpWrite" in e for e in events), events
        assert any("rx MOSDOpReply" in e for e in events), events
        # OpTracker joined: the primary's per-op stages appear
        assert any(e.startswith("op ") or ": " in e
                   for e in events), events
        # timeline is time-ordered with the client's rx of the reply
        # after the first osd rx of the op
        t_op = min(r["t"] for r in rows if "rx MOSDOp" in r["event"])
        t_reply = max(r["t"] for r in rows
                      if "rx MOSDOpReply" in r["event"])
        assert t_reply >= t_op
        # an UNRELATED op records nothing into this trace
        io.write_full("untraced", b"u")
        assert len(tracing.dump(tid)) == len(rows)
        # the admin-socket surface serves the same stitched timeline
        dump = c.osds[0].ctx.admin.execute("dump_traces",
                                           trace_id=str(tid))
        assert dump == rows or len(dump) >= len(rows)
    finally:
        c.stop()


def test_trace_ctx_is_thread_scoped():
    assert tracing.current() == 0
    with tracing.trace_ctx() as tid:
        assert tracing.current() == tid
        with tracing.trace_ctx(99) as inner:
            assert inner == 99 and tracing.current() == 99
        assert tracing.current() == tid
    assert tracing.current() == 0
