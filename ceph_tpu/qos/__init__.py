"""Distributed multi-tenant QoS (src/dmclock + osd/scheduler analog).

The control plane over the async data paths: per-tenant
(reservation, weight, limit) profiles distributed in the OSDMap
(``ceph qos set/rm/ls``), dmClock (delta, rho) tags carried on every
MOSDOp so reservations hold cluster-wide, tenant lanes stamped by the
RGW front, and the mClock scheduler in ``ceph_tpu.osd.op_queue``
arbitrating each OSD's shard queues by phase.

See docs/QOS.md for the tag algebra, wire format, commands, and
metric families.
"""

from ceph_tpu.qos.dmclock import (
    PHASE_LIMIT, PHASE_NAMES, PHASE_NONE, PHASE_RESERVATION,
    PHASE_WEIGHT, QosProfile, ServiceTracker, profiles_from_db)

__all__ = [
    "PHASE_LIMIT", "PHASE_NAMES", "PHASE_NONE", "PHASE_RESERVATION",
    "PHASE_WEIGHT", "QosProfile", "ServiceTracker", "profiles_from_db",
]
