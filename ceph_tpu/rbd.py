"""librbd-lite — block images striped over RADOS objects
(src/librbd/ analog: ImageRequest -> ObjectRequest over a striped
layout; header object + rbd_data.<id>.<objno> data objects).

An image is a fixed-size virtual block device: create/open/read/write
at arbitrary byte offsets, resize, stat, remove.  On top of the basic
I/O path:

  * rbd_directory — pool-level image registry (librbd's rbd_directory
    omap object), so `list_images` needs no name probes
  * exclusive lock — the managed lock over the cls lock object class
    on the header (librbd ManagedLock/ExclusiveLock): acquire/release/
    break, and writes refuse while another owner holds it
  * snapshots — snap_create/list/remove/rollback + read(snap=...),
    riding pool snapshots namespaced per image (`rbd.<image>.<snap>`),
    with the image size frozen in the header's snap table
  * clone — COW layering (CloneRequest/CopyupRequest): a child links
    to a PROTECTED parent@snap and shares its objects; reads fall
    through to the parent, the first write to an object copies it up,
    and `flatten` severs the link.  Child snapshots freeze their own
    parent record, so flatten/resize of the head never rewrites what a
    snap could see
"""

from __future__ import annotations

import binascii
import json

from ceph_tpu.osdc.journaler import Journaler
from ceph_tpu.osdc.striper import StripeLayout, StripedObject

RBD_DIRECTORY = "rbd_directory"
#: pool-level parent@snap -> [child image names] registry (the
#: reference's rbd_children object)
RBD_CHILDREN = "rbd_children"

#: image feature bits (librbd feature flags; journaling gates the
#: write-ahead event journal that rbd-mirror replays; object-map keeps
#: the per-object allocation bitmap, fast-diff derives diffs from it)
FEATURE_JOURNALING = "journaling"
FEATURE_OBJECT_MAP = "object-map"
FEATURE_FAST_DIFF = "fast-diff"


class Image:
    HEADER_FMT = "rbd_header.{name}"
    DATA_FMT = "rbd_data.{name}"

    def __init__(self, ioctx, name: str):
        self.io = ioctx
        self.name = name
        self._meta = None

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def create(cls, ioctx, name: str, size: int,
               order: int = 22, stripe_unit: int = 1 << 16,
               stripe_count: int = 4, primary: bool = True,
               features: list[str] | None = None) -> "Image":
        """order = log2(object size), like rbd create --order.
        primary=False creates a demoted replication target atomically
        (no primary window for a mirror-daemon crash to leave open)."""
        header = cls.HEADER_FMT.format(name=name)
        exists = True
        try:
            ioctx.stat(header)
        except OSError:
            exists = False
        if exists:
            raise FileExistsError(f"image {name!r} exists")
        meta = {"size": size, "order": order,
                "stripe_unit": stripe_unit,
                "stripe_count": stripe_count, "snaps": {},
                "features": list(features or []), "primary": primary}
        ioctx.write_full(header, json.dumps(meta).encode())
        ioctx.set_omap(RBD_DIRECTORY, {name: b"1"})
        img = cls(ioctx, name)
        img._meta = meta
        if FEATURE_OBJECT_MAP in (features or []):
            # feature present from birth (clone inheritance, mirror
            # targets): the map object must exist even before the first
            # write, or du/diff on the fresh image error out
            from ceph_tpu.rbd_object_map import ObjectMap
            om = ObjectMap(ioctx, name)
            om.resize(img._striped().layout.num_objects(size))
            om.save()
        return img

    def _load(self) -> dict:
        if self._meta is None:
            blob = self.io.read(self.HEADER_FMT.format(name=self.name))
            self._meta = json.loads(blob.decode())
        return self._meta

    def _striped(self) -> StripedObject:
        m = self._load()
        layout = StripeLayout(stripe_unit=m["stripe_unit"],
                              stripe_count=m["stripe_count"],
                              object_size=1 << m["order"])
        return StripedObject(self.io, self.DATA_FMT.format(name=self.name),
                             layout)

    # -- features / journaling (librbd/Journal.h:43 analog) -------------------

    JOURNAL_FMT = "journal_rbd.{name}"

    def features(self) -> list[str]:
        return list(self._load().get("features", []))

    def feature_enable(self, feature: str) -> None:
        m = self._load()
        feats = m.setdefault("features", [])
        if feature in feats:
            return
        if feature == FEATURE_FAST_DIFF \
                and FEATURE_OBJECT_MAP not in feats:
            raise ValueError("fast-diff requires object-map")
        feats.append(feature)
        if feature == FEATURE_JOURNALING:
            j = self._journal()
            try:
                j.open()
            except OSError:
                j.create()
        self._save_meta(m)
        if feature == FEATURE_OBJECT_MAP:
            # build the map from reality on enable (ObjectMap<I>::open
            # falls back to a rebuild when the map object is absent)
            self.rebuild_object_map()

    def feature_disable(self, feature: str) -> None:
        m = self._load()
        if feature in m.get("features", []):
            if feature == FEATURE_OBJECT_MAP \
                    and FEATURE_FAST_DIFF in m["features"]:
                raise ValueError("disable fast-diff first")
            m["features"].remove(feature)
            self._save_meta(m)
            if feature == FEATURE_OBJECT_MAP:
                from ceph_tpu.rbd_object_map import ObjectMap
                ObjectMap(self.io, self.name).remove()
                for ent in m.get("snaps", {}).values():
                    ObjectMap(self.io, self.name,
                              ent["snapid"]).remove()
                self._om_invalidate()

    def _journal(self) -> Journaler:
        return Journaler(self.io, self.JOURNAL_FMT.format(name=self.name))

    def _journal_event(self, event: dict) -> None:
        """Write-ahead: mutations on a journaled image append the event
        and flush BEFORE touching image data (librbd Journal ordering);
        rbd-mirror replays these on the peer cluster.  Events carry
        absolute offsets/states so replay is idempotent."""
        if FEATURE_JOURNALING not in self._load().get("features", []):
            return
        j = self._journal()
        try:
            j.open()
        except OSError:
            j.create()   # feature set at create-time (mirror targets)
        j.append_entry(json.dumps(event).encode())
        j.flush()

    # -- primary / demote (rbd mirror promote/demote) -------------------------

    def is_primary(self) -> bool:
        return bool(self._load().get("primary", True))

    def promote(self) -> None:
        m = self._load()
        m["primary"] = True
        self._save_meta(m)

    def demote(self) -> None:
        """Non-primary images are read-only replication targets; only
        the mirror daemon's replay applies to them (mirror_apply)."""
        m = self._load()
        m["primary"] = False
        self._save_meta(m)

    def _check_primary(self) -> None:
        # re-read the header: another handle (the mirror daemon, an
        # operator CLI) may have demoted us — librbd learns this through
        # its header watch; here a read per gated mutation is the analog
        self._meta = None
        if not self._load().get("primary", True):
            raise OSError(30, f"image {self.name!r} is non-primary "
                              "(demoted mirror target)")  # EROFS

    # -- I/O ------------------------------------------------------------------

    def stat(self) -> dict:
        m = self._load()
        return {"size": m["size"], "order": m["order"],
                "stripe_unit": m["stripe_unit"],
                "stripe_count": m["stripe_count"],
                "features": list(m.get("features", [])),
                "primary": m.get("primary", True)}

    def _om_invalidate(self) -> None:
        self._om_cache = None

    def _om_enabled(self) -> bool:
        return FEATURE_OBJECT_MAP in self._load().get("features", [])

    def _om_load(self, snapid: int = 0):
        from ceph_tpu.rbd_object_map import ObjectMap
        try:
            return ObjectMap.load(self.io, self.name, snapid)
        except (OSError, ValueError):
            return None

    def _om_mark_write(self, offset: int, length: int) -> None:
        """Write-ahead map update: touched objects go EXISTS before any
        data byte lands (ObjectMap::aio_update pre-write) — a crash
        between map and data can only over-report.  A missing/corrupt
        map is REBUILT from the backing objects first: silently starting
        a fresh empty map here would under-report every earlier write
        and turn a later clone/export-diff into data loss."""
        if not self._om_enabled() or length <= 0:
            return
        from ceph_tpu.rbd_object_map import OBJECT_EXISTS
        om = getattr(self, "_om_cache", None)
        if om is None:
            om = self._om_load()
            if om is None:
                self.rebuild_object_map()
                om = self._om_load()
                if om is None:
                    return   # map stays absent; du/diff will error loudly
        st = self._striped()
        dirty = False
        for objno, _off, _n in st.layout.extents(offset, length):
            if om.get(objno) != OBJECT_EXISTS:
                om.set(objno, OBJECT_EXISTS)
                dirty = True
        if dirty:
            om.save()
        # the exclusive-lock holder owns the map (librbd keeps it in
        # memory under the lock); lockless handles reload per write
        if getattr(self, "_owner", None) is not None:
            self._om_cache = om
        else:
            self._om_cache = None

    def write(self, data: bytes, offset: int = 0) -> int:
        self._check_primary()   # refreshes the header cache too
        m = self._load()
        if offset + len(data) > m["size"]:
            raise ValueError("write past end of image")
        self._check_lock()
        self._journal_event({"op": "write", "off": offset,
                             "data": binascii.hexlify(data).decode()})
        self._om_mark_write(offset, len(data))
        self._copyup(offset, len(data))
        self._striped().write(data, offset)
        return len(data)

    def mirror_apply(self, event: dict) -> None:
        """Apply one replayed journal event (rbd-mirror's Replayer):
        bypasses the primary gate — replication IS how a demoted image
        changes — but still respects sizes and is idempotent."""
        op = event["op"]
        if op == "write":
            data = binascii.unhexlify(event["data"])
            m = self._load()
            end = event["off"] + len(data)
            if end > m["size"]:
                m["size"] = end
                self._save_meta(m)
            self._om_mark_write(event["off"], len(data))
            self._striped().write(data, event["off"])
        elif op == "resize":
            m = self._load()
            if event["size"] < m["size"]:
                self._striped().truncate(event["size"])
            m["size"] = event["size"]
            self._save_meta(m)
        elif op == "snap_create":
            if event["snap"] not in self.snap_list():
                self._snap_create_internal(event["snap"])
        elif op == "snap_remove":
            if event["snap"] in self.snap_list():
                self._snap_remove_internal(event["snap"])
        elif op == "snap_rollback":
            # the target rolls back against ITS copy of the snapshot
            # (created by the replayed snap_create at the same journal
            # position, so contents match the primary's at rollback time)
            self._snap_rollback_internal(event["snap"])
        else:
            raise ValueError(f"unknown journal event {op!r}")

    def read(self, offset: int = 0, length: int = 0,
             snap: str | None = None) -> bytes:
        m = self._load()
        snapid = 0
        size = m["size"]
        if snap is not None:
            ent = m.get("snaps", {}).get(snap)
            if ent is None:
                raise KeyError(f"no snapshot {snap!r}")
            snapid, size = ent["snapid"], ent["size"]
        if length <= 0 or offset + length > size:
            length = max(0, size - offset)
        # clone layering: a SNAP read uses the parent record frozen in
        # that snap entry (flatten/shrink only rewrite the head's);
        # a head read uses the live head record
        if snap is not None:
            prec = m.get("snaps", {}).get(snap, {}).get("parent")
        else:
            prec = m.get("parent")
        if prec:
            return self._clone_read(offset, length, snapid, prec)
        data = self._striped().read(offset, length, snapid=snapid)
        if len(data) < length:      # unwritten space reads as zeros
            data = data + bytes(length - len(data))
        return data

    # -- exclusive lock (librbd ManagedLock over cls lock) --------------------

    def _header(self) -> str:
        return self.HEADER_FMT.format(name=self.name)

    def lock_acquire(self, owner: str) -> None:
        self.io.execute(self._header(), "lock", "lock",
                        json.dumps({"owner": owner}).encode())
        self._owner = owner

    def lock_release(self, owner: str | None = None) -> None:
        self.io.execute(self._header(), "lock", "unlock",
                        json.dumps({"owner": owner
                                    or getattr(self, "_owner",
                                               None)}).encode())
        self._owner = None
        self._om_invalidate()

    def lock_info(self) -> dict:
        return json.loads(self.io.execute(self._header(), "lock", "info"))

    def break_lock(self) -> None:
        """Steal a dead client's lock (rbd lock break)."""
        holder = self.lock_info().get("holder")
        if holder:
            self.io.execute(self._header(), "lock", "unlock",
                            json.dumps({"owner": holder}).encode())

    def _check_lock(self) -> None:
        """Writes respect an exclusive lock held by another owner.  A
        handle that holds the lock itself skips the round trip (its
        ownership stands until it releases; a concurrent break_lock is
        the operator declaring this writer dead, as in the reference,
        where the broken client is blocklisted).  Any other handle pays
        one lock_info per write — correctness over latency here."""
        if getattr(self, "_owner", None) is not None:
            return
        try:
            holder = self.lock_info().get("holder")
        except OSError:
            holder = None
        if holder is not None:
            raise OSError(16, f"image locked by {holder!r}")  # EBUSY

    # -- snapshots (pool snaps namespaced per image) --------------------------

    def _save_meta(self, m: dict) -> None:
        self.io.write_full(self._header(), json.dumps(m).encode())
        self._meta = m

    def snap_create(self, snap: str) -> int:
        self._check_primary()
        snapid = self._snap_create_internal(snap)
        # journal AFTER the mon op succeeds: a failed snap must never
        # replay onto the mirror (the reverse window — snap taken, crash
        # before journaling — loses only the mirror's copy of the snap,
        # the recoverable direction)
        self._journal_event({"op": "snap_create", "snap": snap})
        return snapid

    def _snap_create_internal(self, snap: str) -> int:
        """Snapshot without the primary gate or journaling: the public
        path wraps this; mirror replay (mirror_apply) calls it directly
        so replicated snaps neither re-journal on the target nor bounce
        off its demoted state."""
        m = self._load()
        if snap in m.get("snaps", {}):
            raise FileExistsError(f"snapshot {snap!r} exists")
        rc, out = self.io.client.mon_command({
            "prefix": "osd pool mksnap", "pool": self.io.pool_id,
            "snap": f"rbd.{self.name}.{snap}"})
        if rc != 0:
            raise OSError(-rc or 5, out)
        reply = json.loads(out)
        snapid = reply["snapid"]
        # map-propagation barrier: a write issued right after this must
        # carry the post-snap epoch, or a stale primary could skip the
        # pre-write COW clone and silently corrupt the snapshot
        if "epoch" in reply:
            self.io.client.wait_for_epoch(reply["epoch"])
        ent = {"snapid": snapid, "size": m["size"]}
        if m.get("parent"):
            # freeze the parent linkage AS OF this snapshot: a later
            # flatten or shrink (which rewrites the head's parent
            # record) must never change what this snap reads
            ent["parent"] = dict(m["parent"])
        m.setdefault("snaps", {})[snap] = ent
        self._save_meta(m)
        if self._om_enabled():
            om = self._om_load()
            if om is not None:
                # freeze the map under the snap; head EXISTS demote to
                # EXISTS_CLEAN so fast-diff can tell dirty from clean
                om.snapshot_copy(snapid)
                self._om_invalidate()
        return snapid

    def snap_list(self) -> dict:
        return dict(self._load().get("snaps", {}))

    def snap_remove(self, snap: str) -> None:
        self._check_primary()
        self._snap_remove_internal(snap)
        self._journal_event({"op": "snap_remove", "snap": snap})

    def _snap_remove_internal(self, snap: str) -> None:
        m = self._load()
        if snap not in m.get("snaps", {}):
            raise KeyError(f"no snapshot {snap!r}")
        if m["snaps"][snap].get("protected"):
            raise OSError(16, f"snapshot {snap!r} is protected "
                          "(unprotect first)")   # EBUSY
        rc, out = self.io.client.mon_command({
            "prefix": "osd pool rmsnap", "pool": self.io.pool_id,
            "snap": f"rbd.{self.name}.{snap}"})
        if rc != 0:
            raise OSError(-rc or 5, out)
        snapid = m["snaps"][snap]["snapid"]
        removed_prec = m["snaps"][snap].get("parent")
        del m["snaps"][snap]
        self._save_meta(m)
        if removed_prec and not m.get("parent") \
                and not any(e.get("parent")
                            for e in m.get("snaps", {}).values()):
            # the last parent-referencing snap of a flattened clone is
            # gone: nothing of this image reads the parent any more —
            # release the children registration that blocked unprotect
            Image(self.io, removed_prec["image"])._unregister_child(
                removed_prec["snap"], self.name)
        from ceph_tpu.rbd_object_map import (
            OBJECT_EXISTS, OBJECT_EXISTS_CLEAN, OBJECT_PENDING,
            ObjectMap)
        if self._om_enabled():
            # the removed map's dirty bits record "changed since the
            # PREVIOUS snap"; fold them into the next-younger map (or
            # the head) so a later diff spanning this hole still sees
            # the rewrite (the reference re-flags clean objects the
            # same way when a snap in the middle goes away)
            gone = self._om_load(snapid)
            if gone is not None:
                younger = [e["snapid"] for e in m["snaps"].values()
                           if e["snapid"] > snapid]
                heir = self._om_load(min(younger)) if younger \
                    else self._om_load()
                if heir is not None:
                    dirty = False
                    for objno in range(gone.n_objs):
                        if gone.get(objno) in (OBJECT_EXISTS,
                                               OBJECT_PENDING) \
                                and heir.get(objno) \
                                == OBJECT_EXISTS_CLEAN:
                            heir.set(objno, OBJECT_EXISTS)
                            dirty = True
                    if dirty:
                        heir.save()
                    self._om_invalidate()
            ObjectMap(self.io, self.name, snapid).remove()

    def snap_rollback(self, snap: str) -> None:
        """Restore image content to the snapshot (rbd snap rollback —
        object-by-object copy-back, librbd's simple_rollback).  On a
        journaled image the rollback is journaled like any other mutation
        (write-ahead, before the data moves): the mirror replays it
        against its own replicated snapshot, so the pair stays converged
        instead of silently diverging on an unjournaled full rewrite."""
        self._check_primary()
        if snap not in self._load().get("snaps", {}):
            raise KeyError(f"no snapshot {snap!r}")
        self._check_lock()
        self._journal_event({"op": "snap_rollback", "snap": snap})
        self._snap_rollback_internal(snap)

    def _snap_rollback_internal(self, snap: str) -> None:
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        data = self.read(0, ent["size"], snap=snap)
        st = self._striped()
        self._om_mark_write(0, max(ent["size"], m["size"]))
        st.truncate(0)
        st.write(data, 0)
        m["size"] = ent["size"]
        self._save_meta(m)

    # -- snapshot protection + COW clone layering -----------------------------
    # (src/librbd/image/CloneRequest.cc:80-220 parent linkage,
    #  src/librbd/io/CopyupRequest.cc:120-260 first-write copy-up,
    #  src/librbd/Operations.cc snap_protect/unprotect/flatten)

    def snap_protect(self, snap: str) -> None:
        """Mark a snapshot clone-able: children may link to it, and it
        cannot be removed until unprotected (which in turn requires no
        children)."""
        self._check_primary()
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        ent["protected"] = True
        self._save_meta(m)

    def snap_unprotect(self, snap: str) -> None:
        self._check_primary()
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        if self.list_children(snap):
            raise OSError(16, f"snapshot {snap!r} has children")  # EBUSY
        ent["protected"] = False
        self._save_meta(m)

    def snap_is_protected(self, snap: str) -> bool:
        ent = self._load().get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        return bool(ent.get("protected"))

    @staticmethod
    def _children_key(parent: str, snap: str) -> str:
        return f"{parent}@{snap}"

    def list_children(self, snap: str) -> list[str]:
        """Child images cloned from parent@snap (rbd children)."""
        try:
            omap = self.io.get_omap(RBD_CHILDREN)
        except OSError:
            return []
        blob = omap.get(self._children_key(self.name, snap))
        return json.loads(blob.decode()) if blob else []

    def _register_child(self, snap: str, child: str) -> None:
        kids = self.list_children(snap)
        if child not in kids:
            kids.append(child)
            self.io.set_omap(RBD_CHILDREN, {
                self._children_key(self.name, snap):
                json.dumps(kids).encode()})

    def _unregister_child(self, snap: str, child: str) -> None:
        kids = self.list_children(snap)
        if child in kids:
            kids.remove(child)
            key = self._children_key(self.name, snap)
            if kids:
                self.io.set_omap(RBD_CHILDREN, {
                    key: json.dumps(kids).encode()})
            else:
                try:
                    self.io.rm_omap_keys(RBD_CHILDREN, [key])
                except OSError:
                    pass

    def _parent(self) -> tuple["Image", str, int] | None:
        """(parent image, parent snap, overlap bytes) for a clone."""
        p = self._load().get("parent")
        if not p:
            return None
        return Image(self.io, p["image"]), p["snap"], int(p["overlap"])

    def _obj_name(self, objno: int) -> str:
        st = self._striped()
        return st.striper.object_name(
            self.DATA_FMT.format(name=self.name), objno)

    def _obj_exists(self, objno: int) -> bool:
        try:
            self.io.stat(self._obj_name(objno))
            return True
        except OSError:
            return False

    def _copyup(self, offset: int, length: int) -> None:
        """First write to a clone-backed object pulls the parent's
        bytes for that WHOLE object into the child first (CopyupRequest
        ordering: copy-up, then the client write overwrites its part) —
        after which reads of the object's other ranges come from the
        child, never a torn child/parent mix."""
        parent = self._parent()
        if parent is None or length <= 0:
            return
        parent_img, psnap, overlap = parent
        m = self._load()
        st = self._striped()
        span = min(overlap, m["size"])
        end = offset + length
        touched = {objno for objno, _o, _n in
                   st.layout.extents(offset, length)}
        for objno in sorted(touched):
            if self._obj_exists(objno):
                continue
            extents = st.layout.object_logical_extents(objno, span)
            if all(offset <= lo and lo + ln <= end
                   for lo, ln in extents):
                # the incoming write fully covers this object's bytes:
                # nothing parent-backed survives it (CopyupRequest's
                # full-overwrite fast path)
                continue
            self._materialize_object(st, extents, parent_img, psnap)

    def _materialize_object(self, st, extents, parent_img,
                            psnap: str, mark_om: bool = False) -> bool:
        """Pull one object's parent-backed bytes into the child (the
        shared copy-up/flatten loop).  All-zero parent bytes create no
        object — reads keep falling through to the parent's zeros, and
        a rerun is idempotent.  Returns True if anything was written."""
        wrote = False
        for log_off, ln in extents:
            data = parent_img.read(log_off, ln, snap=psnap)
            if data.rstrip(b"\x00"):
                if mark_om:
                    self._om_mark_write(log_off, ln)
                st.write(data, log_off)
                wrote = True
        return wrote

    def _clone_read(self, offset: int, length: int, snapid: int,
                    prec: dict) -> bytes:
        """Clone read path: objects the child has are served locally;
        missing objects (or objects with no state at the requested
        child snap) read THROUGH to parent@snap, clipped to the
        overlap (beyond it the clone reads zeros).  prec is the parent
        record governing THIS read (the head's, or the one frozen in
        the child snap being read)."""
        parent_img = Image(self.io, prec["image"])
        psnap, overlap = prec["snap"], int(prec["overlap"])
        st = self._striped()
        parts: list[bytes] = []
        pos = offset
        for objno, obj_off, n in st.layout.extents(offset, length):
            chunk: bytes | None = None
            if self._obj_exists(objno):
                try:
                    chunk = self.io.read(self._obj_name(objno),
                                         length=n, offset=obj_off,
                                         snapid=snapid)
                except OSError:
                    chunk = None    # no state at that child snap
            if chunk is None:
                if pos < overlap:
                    pn = min(n, overlap - pos)
                    chunk = parent_img.read(pos, pn, snap=psnap)
                else:
                    chunk = b""
            if len(chunk) < n:
                chunk = chunk + bytes(n - len(chunk))
            parts.append(chunk)
            pos += n
        return b"".join(parts)

    def clone(self, dst_name: str, snap: str) -> "Image":
        """COW clone (CloneRequest.cc): the child links to
        parent@snap and shares its objects — no data is copied.  Reads
        fall through to the parent; the first write to an object
        copies it up (see _copyup); `flatten` severs the link.  The
        snapshot must be PROTECTED first (and stays unremovable while
        children exist)."""
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        if not ent.get("protected"):
            raise OSError(22, f"snapshot {snap!r} is not protected")
        inherit = [f for f in m.get("features", [])
                   if f in (FEATURE_OBJECT_MAP, FEATURE_FAST_DIFF)]
        dst = Image.create(self.io, dst_name, size=ent["size"],
                           order=m["order"], stripe_unit=m["stripe_unit"],
                           stripe_count=m["stripe_count"],
                           features=inherit)
        dm = dst._load()
        dm["parent"] = {"image": self.name, "snap": snap,
                        "snapid": ent["snapid"],
                        "overlap": ent["size"]}
        dst._save_meta(dm)
        self._register_child(snap, dst_name)
        return dst

    def flatten(self) -> int:
        """Copy every still-parent-backed object into the child's HEAD
        and sever the head's parent link (librbd flatten — the explicit
        end of thin provisioning).  Returns objects materialized.

        Child snapshots keep the parent record frozen at their
        creation, so their view survives the flatten — and while any
        such snap exists the child stays in the parent's children
        registry, keeping unprotect refused (the reference's
        snapshots-remain-clones semantics)."""
        parent = self._parent()
        if parent is None:
            return 0
        self._check_primary()
        self._check_lock()
        parent_img, psnap, overlap = parent
        m = self._load()
        st = self._striped()
        span = min(overlap, m["size"])
        copied = 0
        for objno in range(st.layout.num_objects(span)):
            if self._obj_exists(objno):
                continue
            if self._materialize_object(
                    st, st.layout.object_logical_extents(objno, span),
                    parent_img, psnap, mark_om=True):
                copied += 1
        del m["parent"]
        self._save_meta(m)
        if not any(e.get("parent") for e in
                   m.get("snaps", {}).values()):
            parent_img._unregister_child(psnap, self.name)
        return copied

    def resize(self, new_size: int) -> None:
        self._check_primary()
        m = self._load()
        self._check_lock()
        self._journal_event({"op": "resize", "size": new_size})
        if new_size < m["size"]:
            # shrink trims the discarded extent (real rbd semantics):
            # growing back later must read zeros, not stale payload
            self._striped().truncate(new_size)
            # a clone shrunk below its parent overlap must never grow
            # back into parent bytes it discarded
            p = m.get("parent")
            if p and new_size < int(p["overlap"]):
                p["overlap"] = new_size
        m["size"] = new_size
        self._save_meta(m)
        if self._om_enabled():
            om = self._om_load()
            if om is not None:
                st = self._striped()
                om.resize(st.layout.num_objects(new_size))
                om.save()
            self._om_invalidate()

    # -- object map / fast-diff (src/librbd/object_map/) ----------------------

    def rebuild_object_map(self) -> int:
        """Reconstruct the allocation bitmap from the actual backing
        objects (object_map::RebuildRequest — what `rbd object-map
        rebuild` and scrub-on-corruption run).  Returns objects found."""
        from ceph_tpu.rbd_object_map import OBJECT_EXISTS, ObjectMap
        m = self._load()
        st = self._striped()
        om = ObjectMap(self.io, self.name)
        om.resize(st.layout.num_objects(m["size"]))
        found = 0
        for objno in range(om.n_objs):
            try:
                self.io.stat(st.striper.object_name(st.name, objno))
            except OSError:
                continue
            om.set(objno, OBJECT_EXISTS)
            found += 1
        om.flags = 0     # rebuilt: the map is trustworthy again
        om.save()
        self._om_invalidate()
        return found

    def _om_for(self, snap: str | None):
        """(ObjectMap, size) as of a snapshot name or the head; raises
        if the map is missing/corrupt (callers rebuild or fall back)."""
        m = self._load()
        if snap is None:
            om = self._om_load()
            size = m["size"]
        else:
            ent = m.get("snaps", {}).get(snap)
            if ent is None:
                raise KeyError(f"no snapshot {snap!r}")
            om = self._om_load(ent["snapid"])
            size = ent["size"]
        if om is None:
            raise OSError(5, "object map missing or corrupt "
                             "(run rebuild_object_map)")
        if om.flags & 1:
            raise OSError(5, "object map flagged invalid")
        return om, size

    def diff(self, from_snap: str | None = None,
             to_snap: str | None = None) -> list[tuple[int, int, bool]]:
        """Fast-diff: [(offset, length, exists)] logical extents that
        changed between from_snap (None = the beginning) and to_snap
        (None = head), computed ENTIRELY from object maps — no data
        object is read or stat'ed (DiffRequest semantics).  Walks every
        snapshot map in (from, to]: each map's EXISTS bits are "dirty
        since the previous snap", so intermediate rewrites are caught."""
        from ceph_tpu.rbd_object_map import diff_objnos
        m = self._load()
        snaps = m.get("snaps", {})
        from_id = snaps[from_snap]["snapid"] if from_snap else 0
        to_id = (snaps[to_snap]["snapid"] if to_snap
                 else float("inf"))
        to_om, to_size = self._om_for(to_snap)
        from_om = self._om_for(from_snap)[0] if from_snap else None
        chain = []
        if from_snap:
            for _name, ent in sorted(snaps.items(),
                                     key=lambda kv: kv[1]["snapid"]):
                sid = ent["snapid"]
                if from_id < sid and sid < to_id:
                    om = self._om_load(sid)
                    if om is None:
                        # a lost intermediate map would silently drop
                        # rewrites made in its window: fail loudly like
                        # the endpoint maps do
                        raise OSError(
                            5, f"object map for snapshot id {sid} "
                               "missing or corrupt")
                    chain.append(om)
        chain.append(to_om)
        st = self._striped()
        out: list[tuple[int, int, bool]] = []
        for objno, exists in sorted(
                diff_objnos(from_om, chain).items()):
            for off, ln in st.layout.object_logical_extents(
                    objno, to_size):
                out.append((off, ln, exists))
        out.sort()
        return out

    def du(self, snap: str | None = None) -> dict:
        """Object-granular space usage from the map alone (`rbd du`
        with fast-diff: no per-object stats)."""
        om, size = self._om_for(snap)
        obj_size = 1 << self._load()["order"]
        present = om.count()
        return {"size": size, "used_objects": present,
                "provisioned_objects": om.n_objs,
                "used_bytes": min(present * obj_size, size)}

    def export_diff(self, from_snap: str | None = None,
                    to_snap: str | None = None) -> bytes:
        """Serialized changed-extent stream (`rbd export-diff`): header
        json line + per-extent records, readable by import_diff on any
        image.  Reads ONLY the changed extents' data."""
        recs = []
        m = self._load()
        to_size = (m["size"] if to_snap is None
                   else m["snaps"][to_snap]["size"])
        for off, ln, exists in self.diff(from_snap, to_snap):
            if exists:
                data = self.read(off, ln, snap=to_snap)
                recs.append({"off": off, "len": ln,
                             "data": binascii.hexlify(data).decode()})
            else:
                recs.append({"off": off, "len": ln, "zero": True})
        return json.dumps({"v": 1, "size": to_size,
                           "from": from_snap, "to": to_snap,
                           "extents": recs}).encode()

    def import_diff(self, blob: bytes) -> int:
        """Apply an export_diff stream (`rbd import-diff`).  An
        incremental stream (one exported with from_snap) names its base
        snapshot; the target must HOLD that snapshot or the apply is
        refused — applying a delta onto the wrong base silently yields
        a frankenimage (the reference embeds and checks the start snap
        the same way).  Returns bytes written."""
        doc = json.loads(blob.decode())
        m = self._load()
        base = doc.get("from")
        if base and base not in m.get("snaps", {}):
            raise ValueError(
                f"diff stream is incremental from snapshot {base!r}, "
                f"which this image does not have")
        if doc["size"] != m["size"]:
            self.resize(doc["size"])
        written = 0
        for rec in doc["extents"]:
            if rec.get("zero"):
                self.write(bytes(rec["len"]), rec["off"])
            else:
                self.write(binascii.unhexlify(rec["data"]), rec["off"])
            written += rec["len"]
        return written

    def remove(self) -> None:
        # librbd refuses removal while snapshots exist: the pool snaps
        # are only reachable through this header's name->snapid table
        if self._load().get("snaps"):
            raise OSError(16, "image has snapshots (remove them first)")
        self._check_lock()   # and while another owner holds the lock
        parent = self._parent()
        if parent is not None:
            parent_img, psnap, _ov = parent
            parent_img._unregister_child(psnap, self.name)
        from ceph_tpu.rbd_object_map import ObjectMap
        ObjectMap(self.io, self.name).remove()
        self._striped().remove()
        try:
            self.io.remove(self.HEADER_FMT.format(name=self.name))
        except OSError:
            pass
        try:
            self.io.rm_omap_keys(RBD_DIRECTORY, [self.name])
        except OSError:
            pass
        self._meta = None


def list_images(ioctx, probe: list[str] | None = None) -> list[str]:
    """Pool image listing from the rbd_directory omap object, unioned
    with probe hits (legacy images created before the directory existed
    still appear, even once the directory object does)."""
    found = set()
    try:
        found.update(ioctx.get_omap(RBD_DIRECTORY))
    except OSError:
        pass
    for name in probe or []:
        if name in found:
            continue
        try:
            ioctx.stat(Image.HEADER_FMT.format(name=name))
            found.add(name)
        except OSError:
            continue
    return sorted(found)
