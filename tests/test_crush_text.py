"""crushtool text-map grammar (CrushCompiler.cc): compile a hand-
written map, decompile-recompile round trips, and mapping equivalence
with builder-constructed maps."""

import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.crush.mapper_ref import crush_do_rule
from ceph_tpu.crush.text import (
    CompileError, CrushNames, compile_text, decompile)

SAMPLE = """
# begin crush map
tunable choose_total_tries 50
tunable chooseleaf_stable 1

# devices
device 0 osd.0 class hdd
device 1 osd.1 class hdd
device 2 osd.2 class ssd
device 3 osd.3 class ssd
device 4 osd.4 class hdd
device 5 osd.5 class hdd

# types
type 0 osd
type 1 host
type 10 root

# buckets
host node-a {
    id -2
    alg straw2
    hash 0  # rjenkins1
    item osd.0 weight 1.000
    item osd.1 weight 2.000
}
host node-b {
    id -3
    alg straw2
    hash 0
    item osd.2 weight 1.000
    item osd.3 weight 1.000
}
host node-c {
    id -4
    alg straw2
    hash 0
    item osd.4 weight 1.000
    item osd.5 weight 1.000
}
root default {
    id -1
    alg straw2
    hash 0
    item node-a weight 3.000
    item node-b weight 2.000
    item node-c weight 2.000
}

# rules
rule replicated_rule {
    id 0
    type replicated
    min_size 1
    max_size 10
    step take default
    step chooseleaf firstn 0 type host
    step emit
}
rule ec_rule {
    id 1
    type erasure
    min_size 3
    max_size 6
    step set_chooseleaf_tries 5
    step take default
    step choose indep 0 type osd
    step emit
}
# end crush map
"""


class TestCompile:
    def test_sample_structure(self):
        m, names = compile_text(SAMPLE)
        assert m.max_devices == 6
        assert names.classes == {0: "hdd", 1: "hdd", 2: "ssd",
                                 3: "ssd", 4: "hdd", 5: "hdd"}
        root = m.bucket(-1)
        assert root is not None and root.items == [-2, -3, -4]
        assert root.weight == 7 * 0x10000
        a = m.bucket(-2)
        assert a.item_weights == [0x10000, 0x20000]
        assert names.items[-2] == "node-a"
        assert m.tunables.choose_total_tries == 50
        r = m.rules[0]
        assert r.steps[0].arg1 == -1          # take default
        assert r.steps[1].arg2 == 1           # type host
        assert m.rules[1].steps[0].arg1 == 5  # set_chooseleaf_tries

    def test_mapping_works(self):
        m, _ = compile_text(SAMPLE)
        for x in range(64):
            out = crush_do_rule(m, 0, x, 3, [0x10000] * 6)
            assert len(out) == 3
            assert len(set(out)) == 3

    def test_declaration_order_free(self):
        # root first, hosts after — reference rejects this, we build
        # children-first regardless
        # move the root block before the host blocks
        lines = SAMPLE.splitlines()
        ri = next(i for i, l in enumerate(lines)
                  if l.startswith("root default"))
        re_ = next(i for i in range(ri, len(lines))
                   if lines[i].strip() == "}") + 1
        hi = next(i for i, l in enumerate(lines)
                  if l.startswith("host node-a"))
        root_blk = lines[ri:re_]
        rest = lines[:ri] + lines[re_:]
        lines2 = rest[:hi] + root_blk + rest[hi:]
        m2, _ = compile_text("\n".join(lines2))
        m1, _ = compile_text(SAMPLE)
        for x in range(32):
            assert crush_do_rule(m1, 0, x, 3, [0x10000] * 6) == \
                crush_do_rule(m2, 0, x, 3, [0x10000] * 6)

    def test_errors(self):
        with pytest.raises(CompileError):
            compile_text("tunable bogus_knob 1")
        with pytest.raises(CompileError):
            compile_text("host h { id -1 alg warp hash 0 }\ntype 1 host")
        with pytest.raises(CompileError):
            # a class no device carries has no shadow tree to take
            compile_text(SAMPLE + "\nrule bad { id 9 type replicated "
                         "min_size 1 max_size 10 "
                         "step take default class nvme step emit }")
        with pytest.raises((CompileError, ValueError)):
            compile_text("rule r { id 0 type replicated min_size 1 "
                         "max_size 10 step take nonexistent step emit }")


class TestRoundTrip:
    def test_text_map_text(self):
        m1, n1 = compile_text(SAMPLE)
        text = decompile(m1, n1)
        m2, n2 = compile_text(text)
        assert n2.items == n1.items
        assert n2.classes == n1.classes
        assert m2.max_devices == m1.max_devices
        for b1 in m1.buckets:
            b2 = m2.bucket(b1.id)
            assert b2.items == b1.items
            assert b2.item_weights == b1.item_weights
            assert (b2.alg, b2.type, b2.weight) == \
                (b1.alg, b1.type, b1.weight)
        for r1, r2 in zip(m1.rules, m2.rules):
            assert [(s.op, s.arg1, s.arg2) for s in r1.steps] == \
                [(s.op, s.arg1, s.arg2) for s in r2.steps]
        for x in range(64):
            assert crush_do_rule(m1, 0, x, 3, [0x10000] * 6) == \
                crush_do_rule(m2, 0, x, 3, [0x10000] * 6)

    def test_builder_map_survives(self):
        crush, _root, rule = build_two_level_map(4, 3)
        text = decompile(crush)       # synthesized names
        m2, _ = compile_text(text)
        w = [0x10000] * 12
        for x in range(128):
            assert crush_do_rule(crush, rule, x, 3, w) == \
                crush_do_rule(m2, rule, x, 3, w)


class TestCrushtoolCli:
    def test_compile_decompile_tree_build(self, tmp_path):
        from ceph_tpu.crush.mapper_ref import crush_do_rule
        from ceph_tpu.tools import crushtool as ct
        txt_path = tmp_path / "map.txt"
        bin_path = str(tmp_path / "map.bin")
        txt_path.write_text(SAMPLE)
        assert ct.main(["-c", str(txt_path), "-o", bin_path]) == 0
        m, names = ct.read_binary(bin_path)
        assert names.items[-2] == "node-a"
        out_path = tmp_path / "out.txt"
        assert ct.main(["-d", bin_path, "-o", str(out_path)]) == 0
        m2, _ = compile_text(out_path.read_text())
        for x in range(32):
            assert crush_do_rule(m, 0, x, 3, [0x10000] * 6) == \
                crush_do_rule(m2, 0, x, 3, [0x10000] * 6)
        tree = "\n".join(ct.tree_lines(m, names))
        assert "root default" in tree and "host node-a" in tree
        # --build layered map maps correctly at device failure domain
        built = str(tmp_path / "b.bin")
        assert ct.main(["--build", "--num-osds", "6", "host", "straw2",
                        "2", "root", "straw2", "0", "-o", built]) == 0
        bm, bn = ct.read_binary(built)
        assert len([b for b in bm.buckets if b is not None]) == 4
        for x in range(32):
            out = crush_do_rule(bm, 0, x, 3, [0x10000] * 6)
            assert len(set(out)) == 3


class TestValidation:
    def test_positive_bucket_id_rejected(self):
        with pytest.raises(CompileError):
            compile_text("type 1 host\nhost h { id 2 alg straw2 hash 0 }")

    def test_duplicate_rule_id_rejected(self):
        dup = ("rule a { id 0 type replicated min_size 1 max_size 10 "
               "step emit }\n") * 2
        with pytest.raises(CompileError):
            compile_text(dup)

    def test_duplicate_bucket_name_rejected(self):
        with pytest.raises(CompileError):
            compile_text("type 1 host\n"
                         "host h { id -1 alg straw2 hash 0 }\n"
                         "host h { id -2 alg straw2 hash 0 }")

    def test_build_without_root_layer_reaches_all_osds(self, tmp_path):
        from ceph_tpu.crush.mapper_ref import crush_do_rule
        from ceph_tpu.tools import crushtool as ct
        out = str(tmp_path / "x.bin")
        assert ct.main(["--build", "--num-osds", "8", "host", "straw2",
                        "2", "-o", out]) == 0
        m, _ = ct.read_binary(out)
        seen = set()
        for x in range(512):
            res = crush_do_rule(m, 0, x, 3, [0x10000] * 8)
            assert len(set(res)) == 3
            seen.update(res)
        assert seen == set(range(8)), "implicit root left subtrees dark"


class TestSetCrushmap:
    def test_inject_compiled_map_live(self, tmp_path):
        """crushtool -c -> ceph osd setcrushmap -> placement follows the
        operator's map; getcrushmap round-trips (OSDMonitor
        prepare_newcrush path)."""
        import time

        from ceph_tpu.tools import crushtool as ct
        from ceph_tpu.tools.ceph_cli import main as ceph
        from ceph_tpu.tools.vstart import MiniCluster
        c = MiniCluster(n_osds=6, ms_type="async").start()
        try:
            c.wait_for_osd_count(6)
            client = c.client(timeout=15.0)
            pool = c.create_pool(client, pg_num=8, size=3)
            io = client.open_ioctx(pool)
            for i in range(6):
                io.write_full(f"s{i}", b"pre-swap" * 50)
            # compile the 3-host map and inject it
            txt = tmp_path / "m.txt"
            txt.write_text(SAMPLE)
            binp = str(tmp_path / "m.bin")
            assert ct.main(["-c", str(txt), "-o", binp]) == 0
            rc = ceph(["-m", c.mon_host, "-i", binp,
                       "osd", "setcrushmap"])
            assert rc == 0
            # bad map (pool rule missing) rejected
            from ceph_tpu.crush.text import compile_text
            m_norule, n_norule = compile_text(
                SAMPLE.split("# rules")[0] + "\n")
            nobin = str(tmp_path / "no.bin")
            ct.write_binary(nobin, m_norule, n_norule)
            assert ceph(["-m", c.mon_host, "-i", nobin,
                         "osd", "setcrushmap"]) == 22
            # placement now uses the injected hierarchy: every up set
            # spans the three text-map hosts
            from ceph_tpu.balancer import crush_parent
            deadline = time.time() + 10
            while time.time() < deadline:
                m = c.mon.osdmap
                ok = all(
                    len({crush_parent(m, o) for o in
                         m.pg_to_up_acting_osds(pool, ps)[0]}) == 3
                    for ps in range(8))
                if ok:
                    break
                time.sleep(0.2)
            assert ok
            # recovery onto the remapped sets: poll, don't guess
            deadline = time.time() + 12
            intact = set()
            while time.time() < deadline and len(intact) < 6:
                for i in range(6):
                    if i in intact:
                        continue
                    try:
                        if io.read(f"s{i}") == b"pre-swap" * 50:
                            intact.add(i)
                    except OSError:
                        pass
                time.sleep(0.2)
            assert intact == set(range(6))
            # getcrushmap round-trip keeps structure AND names/classes
            outb = str(tmp_path / "got.bin")
            assert ceph(["-m", c.mon_host, "-o", outb,
                         "osd", "getcrushmap"]) == 0
            got, gnames = ct.read_binary(outb)
            assert [b.id for b in got.buckets if b] == \
                [b.id for b in c.mon.osdmap.crush.buckets if b]
            assert gnames.items[-2] == "node-a"
            assert gnames.classes[2] == "ssd"
            from ceph_tpu.crush.text import decompile as _dec
            assert "node-a" in _dec(got, gnames)
            # missing/corrupt -i fails cleanly, not with a traceback
            assert ceph(["-m", c.mon_host, "-i",
                         str(tmp_path / "nope.bin"),
                         "osd", "setcrushmap"]) == 22
            junk = tmp_path / "junk.bin"
            junk.write_bytes(b"garbage")
            assert ceph(["-m", c.mon_host, "-i", str(junk),
                         "osd", "setcrushmap"]) == 22
        finally:
            c.stop()


class TestBootAfterSetcrushmap:
    def test_new_osd_joins_injected_hierarchy(self, tmp_path):
        """A fresh osd booting after setcrushmap must land inside the
        operator's failure-domain shape (crush-location hook default),
        not on a hardcoded legacy root."""
        import time

        from ceph_tpu.balancer import crush_parent
        from ceph_tpu.tools import crushtool as ct
        from ceph_tpu.tools.ceph_cli import main as ceph
        from ceph_tpu.tools.vstart import MiniCluster
        c = MiniCluster(n_osds=6, ms_type="async").start()
        try:
            c.wait_for_osd_count(6)
            client = c.client(timeout=15.0)
            txt = tmp_path / "m.txt"
            txt.write_text(SAMPLE)
            binp = str(tmp_path / "m.bin")
            assert ct.main(["-c", str(txt), "-o", binp]) == 0
            assert ceph(["-m", c.mon_host, "-i", binp,
                         "osd", "setcrushmap"]) == 0
            # boot a 7th osd: it must appear under the injected root in
            # its own host-type bucket (usable by the chooseleaf rule)
            c.run_osd(6)
            deadline = time.time() + 10
            while time.time() < deadline:
                if c.mon.osdmap.is_up(6):
                    break
                time.sleep(0.1)
            m = c.mon.osdmap
            assert m.is_up(6)
            parent = crush_parent(m, 6)
            assert parent is not None, "osd.6 not in any bucket"
            host = m.crush.bucket(parent)
            assert host.type == m.crush.bucket(-2).type  # host type
            gp = crush_parent(m, parent)
            assert gp == -1                              # under root
            # and it can receive data via the host-failure-domain rule
            pool = c.create_pool(client, pg_num=16, size=3)
            io = client.open_ioctx(pool)
            for i in range(12):
                io.write_full(f"j{i}", b"w" * 128)
            from ceph_tpu.balancer import pool_pg_histogram
            hist = pool_pg_histogram(c.mon.osdmap, pool)
            assert 6 in hist, "booted osd receives no placements"
        finally:
            c.stop()


CLASS_RULES = """
rule ssd_rule {
    id 2
    type replicated
    min_size 1
    max_size 10
    step take default class ssd
    step chooseleaf firstn 0 type host
    step emit
}
rule hdd_rule {
    id 3
    type replicated
    min_size 1
    max_size 10
    step take default class hdd
    step choose firstn 0 type osd
    step emit
}
"""


class TestDeviceClasses:
    """Shadow hierarchies (CrushWrapper populate_classes /
    device_class_clone): class-qualified takes place only on devices of
    that class, with the mapper itself class-unaware."""

    def _compile(self):
        text = SAMPLE.replace("# end crush map", CLASS_RULES
                              + "\n# end crush map")
        return compile_text(text)

    def test_shadow_trees_built(self):
        m, names = self._compile()
        assert (-1, "ssd") in m.class_bucket
        assert (-1, "hdd") in m.class_bucket
        ssd_root = m.bucket(m.class_bucket[(-1, "ssd")])
        # only node-b holds ssd devices; empty shadows dropped from items
        assert len(ssd_root.items) == 1
        ssd_host = m.bucket(ssd_root.items[0])
        assert sorted(ssd_host.items) == [2, 3]
        hdd_root = m.bucket(m.class_bucket[(-1, "hdd")])
        hdd_devs = set()
        for h in hdd_root.items:
            hdd_devs.update(m.bucket(h).items)
        assert hdd_devs == {0, 1, 4, 5}
        # weights recompute bottom-up: hdd shadow skips the 2 ssd osds
        assert hdd_root.weight == 5 * 0x10000

    def test_class_rules_place_only_in_class(self):
        m, _names = self._compile()
        rw = [0x10000] * 6
        for x in range(128):
            out = crush_do_rule(m, 2, x, 2, rw)
            assert out and set(out) <= {2, 3}, out
            out = crush_do_rule(m, 3, x, 3, rw)
            assert out and set(out) <= {0, 1, 4, 5}, out

    def test_batched_mapper_class_rule(self):
        """The TPU kernels need no class awareness: shadow trees are
        ordinary buckets."""
        import jax.numpy as jnp
        import numpy as np
        from ceph_tpu.crush.mapper_jax import BatchMapper
        m, _names = self._compile()
        bm = BatchMapper(m)
        xs = jnp.asarray(np.arange(256, dtype=np.uint32))
        rw = jnp.asarray(np.full(6, 0x10000, dtype=np.int64))
        out = np.asarray(bm.do_rule(3, xs, 3, rw))
        valid = out[out >= 0]
        assert set(valid.tolist()) <= {0, 1, 4, 5}
        for x in range(0, 256, 17):
            ref = crush_do_rule(m, 3, x, 3, [0x10000] * 6)
            got = [o for o in out[x] if o >= 0]
            assert got == ref

    def test_decompile_roundtrip_with_classes(self):
        m, names = self._compile()
        text2 = decompile(m, names)
        assert "step take default class ssd" in text2
        assert "step take default class hdd" in text2
        # shadow buckets are hidden from the text form
        assert text2.count("root default {") == 1
        m2, names2 = compile_text(text2)
        rw = [0x10000] * 6
        for x in range(64):
            assert crush_do_rule(m, 2, x, 2, rw) == \
                crush_do_rule(m2, 2, x, 2, rw)
            assert crush_do_rule(m, 3, x, 3, rw) == \
                crush_do_rule(m2, 3, x, 3, rw)

    def test_unknown_class_errors(self):
        text = SAMPLE.replace(
            "# end crush map",
            "rule bad { id 2\n type replicated\n min_size 1\n"
            " max_size 10\n step take default class nvme\n"
            " step emit\n}\n# end crush map")
        with pytest.raises(CompileError):
            compile_text(text)

    def test_codec_roundtrip_with_classes(self):
        from ceph_tpu.msg.encoding import Decoder, Encoder
        from ceph_tpu.osd.map_codec import decode_crush, encode_crush
        m, _names = self._compile()
        e = Encoder()
        encode_crush(m, e)
        m2 = decode_crush(Decoder(e.tobytes()))
        assert m2.class_bucket == m.class_bucket
        rw = [0x10000] * 6
        for x in range(32):
            assert crush_do_rule(m, 2, x, 2, rw) == \
                crush_do_rule(m2, 2, x, 2, rw)
