"""KeyValueDB (src/kv/KeyValueDB.h analog): ordered KV with batched atomic
transactions, backing the mon store.  MemDB for tests; LogDB is a file-backed
append-log with checkpoint compaction (the RocksDB WAL+SST role collapsed to
its durability essentials)."""

from __future__ import annotations

import os
import struct
import threading
import zlib

from ceph_tpu.msg.encoding import Decoder, Encoder


class KVTransaction:
    def __init__(self):
        self.sets: list[tuple[str, str, bytes]] = []    # (prefix, key, value)
        self.rms: list[tuple[str, str]] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.sets.append((prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.rms.append((prefix, key))
        return self

    def encode(self) -> bytes:
        e = Encoder()
        e.list(self.sets, lambda e2, s: (e2.str(s[0]), e2.str(s[1]),
                                         e2.bytes(s[2])))
        e.list(self.rms, lambda e2, r: (e2.str(r[0]), e2.str(r[1])))
        return e.tobytes()

    @staticmethod
    def decode(data: bytes) -> "KVTransaction":
        d = Decoder(data)
        t = KVTransaction()
        t.sets = d.list(lambda d2: (d2.str(), d2.str(), d2.bytes()))
        t.rms = d.list(lambda d2: (d2.str(), d2.str()))
        return t


class KeyValueDB:
    def get_transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, t: KVTransaction) -> None:
        raise NotImplementedError

    def get(self, prefix: str, key: str) -> bytes | None:
        raise NotImplementedError

    def get_range(self, prefix: str) -> dict[str, bytes]:
        """All keys under a prefix, ordered."""
        raise NotImplementedError

    def iterate(self, prefix: str | None = None):
        """Ordered (prefix, key, value) triples — optionally filtered to
        one prefix (KeyValueDB::get_iterator analog)."""
        raise NotImplementedError


class MemDB(KeyValueDB):
    def __init__(self):
        self._data: dict[tuple[str, str], bytes] = {}
        # analysis: allow[bare-lock] -- MemDB map leaf lock
        self._lock = threading.Lock()

    def submit_transaction(self, t: KVTransaction) -> None:
        with self._lock:
            for p, k, v in t.sets:
                self._data[(p, k)] = v
            for p, k in t.rms:
                self._data.pop((p, k), None)

    def get(self, prefix, key):
        with self._lock:
            return self._data.get((prefix, key))

    def get_range(self, prefix):
        with self._lock:
            return {k: v for (p, k), v in sorted(self._data.items())
                    if p == prefix}

    def iterate(self, prefix=None):
        with self._lock:
            return [(p, k, v) for (p, k), v in sorted(self._data.items())
                    if prefix is None or p == prefix]


_FRAME = struct.Struct("<II")


class LogDB(MemDB):
    """Durable MemDB: append-log of encoded transactions + checkpoint."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._log_path = os.path.join(path, "kv.log")
        self._ckpt_path = os.path.join(path, "kv.ckpt")
        self._f = None
        #: replay truncation found by the LAST ``open()``: whether the
        #: replay stopped at a short/corrupt frame with bytes left
        #: behind, and how many bytes were dropped.  The seed broke
        #: out of the loop SILENTLY: a chopped journal looked like a
        #: clean mount while every later transaction was lost.  The
        #: owning store accumulates these into its
        #: ``kv_journal_truncated`` counter at mount.
        self.truncated_frames = 0
        self.truncated_bytes = 0

    def open(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._data.clear()
        self.truncated_frames = 0
        self.truncated_bytes = 0
        if os.path.exists(self._ckpt_path):
            with open(self._ckpt_path, "rb") as f:
                d = Decoder(f.read())
            pairs = d.list(lambda d2: ((d2.str(), d2.str()), d2.bytes()))
            self._data.update(pairs)
        if os.path.exists(self._log_path):
            with open(self._log_path, "rb") as f:
                data = f.read()
            off = 0
            replayed = 0
            while off + _FRAME.size <= len(data):
                length, crc = _FRAME.unpack_from(data, off)
                start = off + _FRAME.size
                blob = data[start:start + length]
                if len(blob) < length or zlib.crc32(blob) != crc:
                    break
                MemDB.submit_transaction(self, KVTransaction.decode(blob))
                off = start + length
                replayed += 1
            leftover = len(data) - off
            if leftover:
                # a torn tail after a crash is one short frame and
                # expected; ANYTHING beyond the stop point is lost
                # either way, so say so loudly instead of presenting a
                # silently shortened history as a clean mount
                self.truncated_frames += 1
                self.truncated_bytes += leftover
                from ceph_tpu.common.logging import dout
                dout("kv", 0,
                     "LogDB %s: replay STOPPED at a short/corrupt "
                     "frame: %d transactions replayed, %d bytes "
                     "unreplayed past offset %d — any transactions "
                     "in those bytes are LOST",
                     self._log_path, replayed, leftover, off)
        self._f = open(self._log_path, "ab")

    def close(self) -> None:
        if self._f:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None

    def submit_transaction(self, t: KVTransaction) -> None:
        blob = t.encode()
        with self._lock:
            assert self._f is not None, "LogDB not open"
            self._f.write(_FRAME.pack(len(blob), zlib.crc32(blob)) + blob)
            self._f.flush()
            os.fsync(self._f.fileno())
        MemDB.submit_transaction(self, t)

    def compact(self) -> None:
        e = Encoder()
        with self._lock:
            e.list(sorted(self._data.items()),
                   lambda e2, kv: (e2.str(kv[0][0]), e2.str(kv[0][1]),
                                   e2.bytes(kv[1])))
            tmp = self._ckpt_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(e.tobytes())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path)
            self._f.close()
            self._f = open(self._log_path, "wb")
