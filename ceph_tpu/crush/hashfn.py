"""rjenkins1 32-bit hash family — the only hash CRUSH uses.

Semantics match src/crush/hash.c exactly: Robert Jenkins' 1997 96-bit mix applied to
fixed seeds (crush_hash_seed = 1315423911, x = 231232, y = 1232) in arity-specific
schedules (hash.c:26-90).  Scalar variants operate on Python ints (the oracle); the
``_vec`` variants are numpy uint32 and broadcast elementwise; the jax variants live in
ops.crush_kernel and are validated against these.
"""

from __future__ import annotations

import numpy as np

CRUSH_HASH_RJENKINS1 = 0
CRUSH_HASH_SEED = 1315423911

_M32 = 0xFFFFFFFF


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    a = (a - b - c) & _M32; a ^= c >> 13
    b = (b - c - a) & _M32; b ^= (a << 8) & _M32
    c = (c - a - b) & _M32; c ^= b >> 13
    a = (a - b - c) & _M32; a ^= c >> 12
    b = (b - c - a) & _M32; b ^= (a << 16) & _M32
    c = (c - a - b) & _M32; c ^= b >> 5
    a = (a - b - c) & _M32; a ^= c >> 3
    b = (b - c - a) & _M32; b ^= (a << 10) & _M32
    c = (c - a - b) & _M32; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= _M32
    h = (CRUSH_HASH_SEED ^ a) & _M32
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= _M32; b &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= _M32; b &= _M32; c &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def crush_hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= _M32; b &= _M32; c &= _M32; d &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= _M32; b &= _M32; c &= _M32; d &= _M32; e &= _M32
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & _M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# ---------------------------------------------------------------------------
# numpy batch variants (uint32 wrap-around arithmetic)
# ---------------------------------------------------------------------------

def _mix_vec(a, b, c):
    with np.errstate(over="ignore"):
        a = a - b - c; a ^= c >> np.uint32(13)
        b = b - c - a; b ^= a << np.uint32(8)
        c = c - a - b; c ^= b >> np.uint32(13)
        a = a - b - c; a ^= c >> np.uint32(12)
        b = b - c - a; b ^= a << np.uint32(16)
        c = c - a - b; c ^= b >> np.uint32(5)
        a = a - b - c; a ^= c >> np.uint32(3)
        b = b - c - a; b ^= a << np.uint32(10)
        c = c - a - b; c ^= b >> np.uint32(15)
    return a, b, c


def crush_hash32_3_vec(a, b, c) -> np.ndarray:
    a = np.asarray(a).astype(np.uint32)
    b = np.asarray(b).astype(np.uint32)
    c = np.asarray(c).astype(np.uint32)
    a, b, c = np.broadcast_arrays(a, b, c)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = np.full_like(h, 231232)
    y = np.full_like(h, 1232)
    a = a.copy(); b = b.copy(); c = c.copy()
    a, b, h = _mix_vec(a, b, h)
    c, x, h = _mix_vec(c, x, h)
    y, a, h = _mix_vec(y, a, h)
    b, x, h = _mix_vec(b, x, h)
    y, c, h = _mix_vec(y, c, h)
    return h


def crush_hash32_2_vec(a, b) -> np.ndarray:
    a = np.asarray(a).astype(np.uint32)
    b = np.asarray(b).astype(np.uint32)
    a, b = np.broadcast_arrays(a, b)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b
    x = np.full_like(h, 231232)
    y = np.full_like(h, 1232)
    a = a.copy(); b = b.copy()
    a, b, h = _mix_vec(a, b, h)
    x, a, h = _mix_vec(x, a, h)
    b, y, h = _mix_vec(b, y, h)
    return h
