"""Thrasher soak in CI (VERDICT round-1 item 8): randomized osd
kill/revive/out/in under a mixed replicated + EC workload; zero lost or
corrupt acked objects after heal."""

from ceph_tpu.tools.thrasher import run_soak


def test_thrasher_soak(tmp_path):
    res = run_soak(duration=18.0, seed=11, n_osds=6,
                   base_path=str(tmp_path))
    assert res["actions"] >= 5, res
    assert res["rep_ops"] > 50, res
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res
