"""Single-decree-pipeline Paxos over the elected quorum
(src/mon/Paxos.{h,cc} collect/begin/accept/commit/lease semantics).

The elected leader drives one proposal at a time:

  on win:  COLLECT(last_committed) -> peons reply LAST {their committed
           tail + any uncommitted value}; the leader adopts newer commits,
           re-proposes a surviving uncommitted value (the Paxos safety
           rule: an accepted-by-majority value must survive leader death),
           catches lagging peons up, then goes active.
  propose: BEGIN(v, blob) -> peons persist the pending value and ACCEPT;
           when the whole quorum accepted, the leader commits and
           broadcasts COMMIT(v, blob).
  lease:   the leader refreshes peon read leases (LEASE/LEASE_ACK);
           a peon whose lease expires calls a new election, a leader
           missing lease acks does the same (liveness after mon death).

Election epochs order leadership; stale-epoch messages are dropped, which
is what the reference's proposal numbers guarantee given one proposer per
epoch.  Values are opaque blobs versioned 1..last_committed in the mon
store ("paxos" prefix), exactly the reference's store layout.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message


@register_message
class MMonPaxos(Message):
    TYPE = 66  # MSG_MON_PAXOS
    HEAD_VERSION = 3       # v3: sync flag (store-sync jump on COMMIT)

    COLLECT = 1
    LAST = 2
    BEGIN = 3
    ACCEPT = 4
    COMMIT = 5
    LEASE = 6
    LEASE_ACK = 7

    def __init__(self, op: int = 0, epoch: int = 0, rank: int = 0,
                 last_committed: int = 0, version: int = 0,
                 value: bytes = b"",
                 values: dict[int, bytes] | None = None,
                 pending_epoch: int = 0, sync: int = 0):
        super().__init__()
        self.op = op
        self.epoch = epoch          # election epoch (proposal ordering)
        self.rank = rank
        self.last_committed = last_committed
        self.version = version      # version being proposed/accepted
        self.value = value          # uncommitted value (LAST/BEGIN)
        self.values = values or {}  # committed catch-up payload
        self.pending_epoch = pending_epoch  # epoch the pending was accepted
        #: v3 (COMMIT only): the sender's history starts above the
        #: receiver's tail — the receiver may JUMP to these values
        #: (legal: every value is a full-state snapshot, not a delta)
        self.sync = sync

    def encode_payload(self, enc: Encoder):
        enc.versioned(3, 1, lambda e: (
            e.u8(self.op), e.u32(self.epoch), e.s32(self.rank),
            e.u64(self.last_committed), e.u64(self.version),
            e.bytes(self.value),
            e.map(self.values, lambda e2, k: e2.u64(k),
                  lambda e2, v: e2.bytes(v)),
            e.u32(self.pending_epoch), e.u8(self.sync)))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.op = d.u8()
            self.epoch = d.u32()
            self.rank = d.s32()
            self.last_committed = d.u64()
            self.version = d.u64()
            self.value = d.bytes()
            self.values = d.map(lambda d2: d2.u64(), lambda d2: d2.bytes())
            self.pending_epoch = d.u32() if v >= 2 else 0
            self.sync = d.u8() if v >= 3 else 0
        dec.versioned(3, body)


STATE_RECOVERING = "recovering"
STATE_ACTIVE = "active"
STATE_UPDATING = "updating"


class Paxos:
    LEASE_INTERVAL = 0.5
    LEASE_TIMEOUT = 3.0
    ACCEPT_TIMEOUT = 3.0

    def __init__(self, rank: int, db, send_fn, on_commit, request_election):
        """db: KV store ("paxos" prefix); send_fn(rank, MMonPaxos);
        on_commit(version, blob) applied on every mon at commit time;
        request_election() called on liveness loss."""
        self.rank = rank
        self.db = db
        self.send = send_fn
        self.on_commit = on_commit
        self.on_active = lambda: None   # leader finished collect phase
        self.request_election = request_election
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"Paxos::lock({rank})")

        self.state = STATE_RECOVERING
        self.is_leader = False
        self.epoch = 0
        self.quorum: list[int] = [rank]
        self.last_committed = 0
        #: accepted-but-uncommitted value: (version, blob, accept_epoch).
        #: The accept epoch is the Paxos proposal number — collect must
        #: keep the HIGHEST-epoch survivor, not the last LAST to arrive
        self.pending: tuple[int, bytes, int] | None = None
        self._load()

        # leader transients
        self._collected: set[int] = set()
        self._collect_started = 0.0
        self._accepted: set[int] = set()
        self._proposing: tuple[int, bytes] | None = None
        self._propose_started = 0.0
        self._queue: list[tuple[bytes, threading.Event, list]] = []
        self._lease_acks: dict[int, float] = {}
        self._last_lease_sent = 0.0
        # peon transient
        self._lease_until = 0.0

    # -- persistence ----------------------------------------------------------

    def _load(self) -> None:
        lc = self.db.get("paxos", "last_committed")
        self.last_committed = int(lc.decode()) if lc else 0
        pv = self.db.get("paxos", "pending_v")
        if pv:
            blob = self.db.get("paxos", "pending_blob")
            pe = self.db.get("paxos", "pending_epoch")
            self.pending = (int(pv.decode()), blob or b"",
                            int(pe.decode()) if pe else 0)

    def get(self, version: int) -> bytes | None:
        return self.db.get("paxos", f"v_{version}")

    def _store_commit(self, version: int, blob: bytes) -> None:
        t = self.db.get_transaction()
        t.set("paxos", f"v_{version}", blob)
        t.set("paxos", "last_committed", str(version).encode())
        t.rmkey("paxos", "pending_v")
        t.rmkey("paxos", "pending_blob")
        t.rmkey("paxos", "pending_epoch")
        self.db.submit_transaction(t)

    def _store_pending(self, version: int, blob: bytes,
                       epoch: int) -> None:
        t = self.db.get_transaction()
        t.set("paxos", "pending_v", str(version).encode())
        t.set("paxos", "pending_blob", blob)
        t.set("paxos", "pending_epoch", str(epoch).encode())
        self.db.submit_transaction(t)

    # -- leadership transitions (driven by the elector) -----------------------

    def leader_init(self, epoch: int, quorum: list[int]) -> None:
        """Election won: run the collect (recovery) phase."""
        with self._lock:
            self.is_leader = True
            self.epoch = epoch
            self.quorum = list(quorum)
            self.state = STATE_RECOVERING
            self._collected = {self.rank}
            self._collect_started = time.time()
            self._accepted = set()
            self._proposing = None
            # seed ack times so a peon that dies right after the election
            # still trips the lease watchdog
            self._lease_acks = {r: time.time() for r in quorum
                                if r != self.rank}
            lc = self.last_committed
        if len(self.quorum) == 1:
            self._collect_done()
            return
        for r in quorum:
            if r != self.rank:
                self.send(r, MMonPaxos(op=MMonPaxos.COLLECT,
                                       epoch=epoch, rank=self.rank,
                                       last_committed=lc))

    def peon_init(self, epoch: int, leader: int, quorum: list[int]) -> None:
        with self._lock:
            self.is_leader = False
            self.epoch = epoch
            self.quorum = list(quorum)
            self.state = STATE_RECOVERING
            self._lease_until = time.time() + self.LEASE_TIMEOUT
            self._proposing = None
            # fail waiters from our leadership days: they must re-submit
            # through the new leader
            drained, self._queue = self._queue, []
        for _blob, ev, _ok in drained:
            ev.set()

    # -- proposing (leader) ---------------------------------------------------

    def propose_and_wait(self, blob: bytes, timeout: float = 10.0) -> bool:
        """Queue a value; returns True once it is committed."""
        ev = threading.Event()
        ok: list = []
        with self._lock:
            if not self.is_leader:
                return False
            self._queue.append((blob, ev, ok))
        self._maybe_propose()
        if not ev.wait(timeout):
            return False
        return bool(ok)

    def _maybe_propose(self) -> None:
        with self._lock:
            if (not self.is_leader or self.state != STATE_ACTIVE
                    or self._proposing is not None or not self._queue):
                return
            blob, ev, ok = self._queue[0]
            version = self.last_committed + 1
            self._proposing = (version, blob)
            self._propose_started = time.time()
            self._accepted = {self.rank}
            self.state = STATE_UPDATING
            self._store_pending(version, blob, self.epoch)
            epoch, quorum = self.epoch, list(self.quorum)
        if len(quorum) == 1:
            self._commit_proposal()
            return
        for r in quorum:
            if r != self.rank:
                self.send(r, MMonPaxos(op=MMonPaxos.BEGIN, epoch=epoch,
                                       rank=self.rank, version=version,
                                       value=blob,
                                       last_committed=version - 1))

    def _commit_proposal(self) -> None:
        with self._lock:
            if self._proposing is None:
                return
            version, blob = self._proposing
            self._proposing = None
            self._store_commit(version, blob)
            self.last_committed = version
            self.state = STATE_ACTIVE
            if self._queue:
                _, ev, ok = self._queue.pop(0)
                ok.append(True)
            else:
                ev = None
            epoch, quorum = self.epoch, list(self.quorum)
        self.on_commit(version, blob)
        for r in quorum:
            if r != self.rank:
                self.send(r, MMonPaxos(op=MMonPaxos.COMMIT, epoch=epoch,
                                       rank=self.rank,
                                       last_committed=version,
                                       values={version: blob}))
        if ev is not None:
            ev.set()
        self._maybe_propose()

    # -- message handling -----------------------------------------------------

    def handle(self, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.epoch < self.epoch:
                return  # stale leadership
            if msg.epoch > self.epoch:
                # I missed an election result; adopt the newer epoch
                self.epoch = msg.epoch
        op = msg.op
        if op == MMonPaxos.COLLECT:
            self._handle_collect(msg)
        elif op == MMonPaxos.LAST:
            self._handle_last(msg)
        elif op == MMonPaxos.BEGIN:
            self._handle_begin(msg)
        elif op == MMonPaxos.ACCEPT:
            self._handle_accept(msg)
        elif op == MMonPaxos.COMMIT:
            self._handle_commit(msg)
        elif op == MMonPaxos.LEASE:
            self._handle_lease(msg)
        elif op == MMonPaxos.LEASE_ACK:
            with self._lock:
                self._lease_acks[msg.rank] = time.time()
                behind = msg.last_committed < self.last_committed
            if behind:
                self.catch_up_peon(msg.rank, msg.last_committed)

    # peon side

    def _handle_collect(self, msg: MMonPaxos) -> None:
        with self._lock:
            reply = MMonPaxos(op=MMonPaxos.LAST, epoch=self.epoch,
                              rank=self.rank,
                              last_committed=self.last_committed)
            if self.pending is not None:
                reply.version, reply.value = self.pending[:2]
                reply.pending_epoch = self.pending[2]
            # catch the new leader up on commits it missed; a store-
            # synced peon with a gap ships its contiguous tail flagged
            # sync so the leader may jump (values are full snapshots)
            for v in range(msg.last_committed + 1, self.last_committed + 1):
                blob = self.get(v)
                if blob is not None:
                    reply.values[v] = blob
                else:
                    reply.sync = 1
                    reply.values.clear()
        self.send(msg.rank, reply)

    def _handle_begin(self, msg: MMonPaxos) -> None:
        with self._lock:
            if msg.version <= self.last_committed:
                return  # already committed (dup)
            self.pending = (msg.version, msg.value, msg.epoch)
            self._store_pending(msg.version, msg.value, msg.epoch)
            epoch = self.epoch
        self.send(msg.rank, MMonPaxos(op=MMonPaxos.ACCEPT, epoch=epoch,
                                      rank=self.rank,
                                      version=msg.version))

    def _handle_commit(self, msg: MMonPaxos) -> None:
        commits: list[tuple[int, bytes]] = []
        with self._lock:
            ordered = sorted(msg.values)
            if msg.sync and ordered and ordered[0] > \
                    self.last_committed + 1:
                # store-sync jump (Monitor.cc sync_start reduced): the
                # sender's history starts above our tail, and every
                # value is a full snapshot — adopt its tail wholesale.
                # Our own pre-jump history stays valid below the gap.
                self.last_committed = ordered[0] - 1
            for v in ordered:
                if v == self.last_committed + 1:
                    blob = msg.values[v]
                    self._store_commit(v, blob)
                    self.last_committed = v
                    commits.append((v, blob))
            if self.pending is not None \
                    and self.pending[0] <= self.last_committed:
                self.pending = None
        for v, blob in commits:
            self.on_commit(v, blob)

    def _handle_lease(self, msg: MMonPaxos) -> None:
        with self._lock:
            self._lease_until = time.time() + self.LEASE_TIMEOUT
            self.state = STATE_ACTIVE if not self.is_leader else self.state
            epoch = self.epoch
        # the ack carries our committed tail; a leader seeing us behind
        # ships the missing values (catch_up_peon on LEASE_ACK)
        self.send(msg.rank, MMonPaxos(op=MMonPaxos.LEASE_ACK, epoch=epoch,
                                      rank=self.rank,
                                      last_committed=self.last_committed))

    # leader side

    def _handle_last(self, msg: MMonPaxos) -> None:
        catch_up: list[tuple[int, MMonPaxos]] = []
        done = False
        with self._lock:
            if not self.is_leader or self.state != STATE_RECOVERING:
                return
            # adopt commits newer than mine (jump over the gap when the
            # peon's synced history starts above my tail)
            ordered = sorted(msg.values)
            if msg.sync and ordered and ordered[0] > \
                    self.last_committed + 1:
                self.last_committed = ordered[0] - 1
            for v in ordered:
                if v == self.last_committed + 1:
                    self._store_commit(v, msg.values[v])
                    self.last_committed = v
                    self.on_commit(v, msg.values[v])
            # a surviving uncommitted value must be re-proposed; when
            # several peons hold conflicting pendings for the same
            # version, Paxos safety requires the HIGHEST accept epoch
            # (it may have been committed by its leader before the crash)
            if msg.version == self.last_committed + 1 and msg.value:
                if (self.pending is None
                        or self.pending[0] != msg.version
                        or msg.pending_epoch >= self.pending[2]):
                    self.pending = (msg.version, msg.value,
                                    msg.pending_epoch)
            self._collected.add(msg.rank)
            if self._collected >= set(self.quorum):
                done = True
        if done:
            self._collect_done()

    def _collect_done(self) -> None:
        with self._lock:
            # re-propose a surviving uncommitted value ahead of the queue
            if self.pending is not None \
                    and self.pending[0] == self.last_committed + 1:
                blob = self.pending[1]
                self._queue.insert(0, (blob, threading.Event(), []))
            self.pending = None
            self.state = STATE_ACTIVE
        # catch lagging peons up and start leases
        self._send_lease()
        self.on_active()
        self._maybe_propose()

    def _handle_accept(self, msg: MMonPaxos) -> None:
        commit = False
        with self._lock:
            if (not self.is_leader or self._proposing is None
                    or msg.version != self._proposing[0]):
                return
            self._accepted.add(msg.rank)
            if self._accepted >= set(self.quorum):
                commit = True
        if commit:
            self._commit_proposal()

    # -- lease / liveness tick ------------------------------------------------

    def _send_lease(self) -> None:
        with self._lock:
            epoch, quorum, lc = self.epoch, list(self.quorum), \
                self.last_committed
            self._last_lease_sent = time.time()
        for r in quorum:
            if r != self.rank:
                # include the committed tail so lagging peons catch up
                self.send(r, MMonPaxos(op=MMonPaxos.LEASE, epoch=epoch,
                                       rank=self.rank, last_committed=lc))

    def tick(self, now: float | None = None) -> None:
        now = now or time.time()
        call_election = False
        recollect: list[int] = []
        with self._lock:
            if (self.is_leader and self.state == STATE_RECOVERING
                    and now - self._collect_started > 1.5):
                # a LAST went missing: retry the stragglers, don't wedge
                self._collect_started = now
                recollect = [r for r in self.quorum
                             if r not in self._collected]
        for r in recollect:
            self.send(r, MMonPaxos(op=MMonPaxos.COLLECT, epoch=self.epoch,
                                   rank=self.rank,
                                   last_committed=self.last_committed))
        with self._lock:
            if self.is_leader:
                if self.state in (STATE_ACTIVE, STATE_UPDATING) \
                        and now - self._last_lease_sent \
                        >= self.LEASE_INTERVAL:
                    send = True
                else:
                    send = False
                # a peon that stopped accepting or acking means the quorum
                # is dead: re-elect to shrink it
                if (self._proposing is not None
                        and now - self._propose_started
                        > self.ACCEPT_TIMEOUT):
                    call_election = True
                for r in self.quorum:
                    if r == self.rank:
                        continue
                    last = self._lease_acks.get(r)
                    if last is not None and now - last > self.LEASE_TIMEOUT:
                        call_election = True
            else:
                send = False
                if now > self._lease_until > 0:
                    call_election = True
                    self._lease_until = now + self.LEASE_TIMEOUT
        if send:
            self._send_lease()
        if call_election:
            self.request_election()

    # -- introspection --------------------------------------------------------

    def catch_up_peon(self, rank: int, from_version: int) -> None:
        """Ship committed values [from_version+1 .. last_committed].
        A leader whose own history starts above from_version (it store-
        synced into the cluster) ships what it has with the sync flag,
        and the peon jumps — correct because values are full
        snapshots."""
        with self._lock:
            values = {}
            missing = False
            for v in range(from_version + 1, self.last_committed + 1):
                blob = self.get(v)
                if blob is not None:
                    values[v] = blob
                else:
                    missing = True
                    values.clear()   # ship only the contiguous tail
            epoch, lc = self.epoch, self.last_committed
        if values:
            self.send(rank, MMonPaxos(op=MMonPaxos.COMMIT, epoch=epoch,
                                      rank=self.rank, last_committed=lc,
                                      values=values,
                                      sync=1 if missing else 0))
