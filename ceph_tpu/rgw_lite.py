"""rgw-lite — object-gateway semantics over RADOS (src/rgw/ analog,
collapsed to the storage mapping: buckets are omap index objects,
gateway objects stripe over RADOS objects, metadata rides omap — the
same rgw_rados.cc layout idea without the HTTP frontends).

Surface: create/delete bucket, put/get/delete/list/head object, with
optional transparent compression via the compressor registry.
"""

from __future__ import annotations

import json
import time

from ceph_tpu import compressor as _compressor
from ceph_tpu.osdc.striper import StripeLayout, StripedObject

#: ONE layout for both put and get — a mismatch would remap logical
#: offsets to different objects between write and read
_LAYOUT = StripeLayout(stripe_unit=1 << 16, stripe_count=2,
                       object_size=1 << 22)


class Bucket:
    INDEX_FMT = ".bucket.index.{name}"

    def __init__(self, ioctx, name: str, compression: str = "none"):
        self.io = ioctx
        self.name = name
        self.comp = _compressor.create(compression)
        self.compression = compression

    # -- bucket lifecycle -----------------------------------------------------

    def create(self) -> "Bucket":
        self.io.set_omap(self.INDEX_FMT.format(name=self.name),
                         {".bucket.meta": json.dumps(
                             {"created": time.time(),
                              "compression": self.compression}).encode()})
        return self

    def exists(self) -> bool:
        try:
            self.io.stat(self.INDEX_FMT.format(name=self.name))
            return True
        except OSError:
            return False

    def delete(self) -> None:
        if self.list():
            raise OSError(39, "bucket not empty")   # ENOTEMPTY
        self.io.remove(self.INDEX_FMT.format(name=self.name))

    # -- objects --------------------------------------------------------------

    def _data_name(self, key: str) -> str:
        return f".bucket.data.{self.name}.{key}"

    def put(self, key: str, data: bytes,
            metadata: dict | None = None) -> None:
        blob = self.comp.compress(data)
        so = StripedObject(self.io, self._data_name(key), _LAYOUT)
        so.remove()
        so.write(blob)
        entry = {"size": len(data), "stored": len(blob),
                 "mtime": time.time(), "meta": metadata or {},
                 "compression": self.comp.name}
        self.io.set_omap(self.INDEX_FMT.format(name=self.name),
                         {f"obj.{key}": json.dumps(entry).encode()})

    def head(self, key: str) -> dict:
        omap = self.io.get_omap(self.INDEX_FMT.format(name=self.name))
        blob = omap.get(f"obj.{key}")
        if not blob:          # absent, or the b"" deletion tombstone
            raise KeyError(key)
        return json.loads(blob.decode())

    def get(self, key: str) -> bytes:
        entry = self.head(key)
        so = StripedObject(self.io, self._data_name(key), _LAYOUT)
        raw = so.read(0, entry["stored"])
        comp = _compressor.create(entry.get("compression", "none"))
        return comp.decompress(raw[:entry["stored"]])

    def delete_object(self, key: str) -> None:
        self.head(key)   # KeyError if absent
        StripedObject(self.io, self._data_name(key), _LAYOUT).remove()
        # omap_rm via set of tombstone: the client API lacks rmkeys;
        # store an explicit deletion marker and filter it in list()
        self.io.set_omap(self.INDEX_FMT.format(name=self.name),
                         {f"obj.{key}": b""})

    def list(self, prefix: str = "") -> list[str]:
        try:
            omap = self.io.get_omap(self.INDEX_FMT.format(name=self.name))
        except OSError:
            return []
        out = []
        for k, v in omap.items():
            if not k.startswith("obj.") or not v:
                continue
            key = k[4:]
            if key.startswith(prefix):
                out.append(key)
        return sorted(out)
