"""Cross-process ICI data plane (msg/ici wire mode — the RDMAStack
role): multi-process OSDs run ms_type=ici end-to-end, EC shard payloads
tokenize and move through per-process jax transfer servers (device
pulls across OS processes), with TCP as the negotiated fallback."""

from __future__ import annotations

import subprocess
import sys
import time

import pytest

from ceph_tpu.tools.vstart import ProcCluster


def _cpu_jax_available() -> bool:
    """The wire data plane needs the jax transfer engine on the cpu
    backend — probe in a subprocess so this process's jax stays
    untouched."""
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from ceph_tpu.msg.ici import IciTransport\n"
        "IciTransport.instance().enable_wire()\n"   # the REAL path
        "print('ok')\n")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=120)
        return "ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


pytestmark = pytest.mark.skipif(
    not _cpu_jax_available(),
    reason="jax transfer engine unavailable on the cpu backend")


def test_two_process_token_pull():
    """The transport primitive on its own: process A stages, process B
    redeems — a device-to-device pull across OS processes."""
    worker = (
        "import sys, os\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=2'\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from ceph_tpu.msg.ici import IciTransport\n"
        "from ceph_tpu.msg.messenger import EntityName\n"
        "t = IciTransport.instance()\n"
        "t.enable_wire()\n"
        "mode = sys.argv[1]\n"
        "if mode == 'stage':\n"
        "    tok = t.stage(bytes(range(256)) * 64, EntityName('osd', 1))\n"
        "    sys.stdout.write(tok.hex() + '\\n')\n"
        "    sys.stdout.flush()\n"
        "    sys.stdin.readline()   # hold until the peer pulled\n"
        "else:\n"
        "    tok = bytes.fromhex(sys.stdin.readline().strip())\n"
        "    data = t.redeem(tok)\n"
        "    assert data == bytes(range(256)) * 64, len(data)\n"
        "    assert t.pulls == 1\n"
        "    print('pulled', len(data))\n")
    a = subprocess.Popen([sys.executable, "-c", worker, "stage"],
                         stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                         text=True)
    tok_line = a.stdout.readline()
    assert tok_line.strip(), "stager produced no token"
    b = subprocess.run([sys.executable, "-c", worker, "redeem"],
                       input=tok_line, capture_output=True, text=True,
                       timeout=120)
    a.stdin.write("done\n")
    a.stdin.close()
    a.wait(timeout=30)
    assert b.returncode == 0, b.stderr
    assert "pulled 16384" in b.stdout


def test_multiprocess_cluster_ec_over_ici(tmp_path):
    """The verdict's acceptance bar: the multi-process vstart tier runs
    ms_type=ici end-to-end — every OSD a separate OS process, EC shard
    payloads moving as transfer-server tokens between them."""
    c = ProcCluster(n_osds=4, base_path=str(tmp_path),
                    ms_type="ici").start()
    try:
        client = c.client()
        c.wait_for_osd_count(4)
        pool = c.create_pool(client, pg_num=1, pool_type="erasure",
                             k=2, m=1)
        io = client.open_ioctx(pool)
        payload = bytes(range(256)) * 128        # 32 KiB: well past
        io.write_full("ici-obj", payload)        # BULK_THRESHOLD
        assert io.read("ici-obj", len(payload)) == payload
        # a second object and an overwrite keep the tokens flowing
        io.write_full("ici-obj2", payload[::-1])
        io.write_full("ici-obj", payload[:16384])
        assert io.read("ici-obj2", len(payload)) == payload[::-1]
        assert io.read("ici-obj", 16384) == payload[:16384]
        # degraded read after a SIGKILL: recovery pushes also ride the
        # wire stack
        c.kill_osd(3)
        deadline = time.time() + 60
        got = None
        while time.time() < deadline:
            try:
                got = io.read("ici-obj2", len(payload))
                if got == payload[::-1]:
                    break
            except (TimeoutError, OSError):
                pass
            time.sleep(0.5)
        assert got == payload[::-1]
    finally:
        c.stop()
