"""Device kernels (JAX/XLA, with Pallas variants for the hot paths).

gf_kernel       batched GF(2^8) matrix-vector products: erasure encode/decode.
crush_kernel    rjenkins1 hashes, crush_ln, straw2 selection — batched over inputs.
"""

from .gf_kernel import (
    ec_encode_ref,
    ec_encode_jax,
    make_encoder,
)

__all__ = ["ec_encode_ref", "ec_encode_jax", "make_encoder"]
