"""Multi-tenant QoS fairness under sustained overload (the dmClock
control plane end-to-end): `ceph qos set` profiles distribute through
the OSDMap, RGW/client tenant lanes tag every op, and the OSD's
dmclock scheduler holds reservation floors, weight-proportional excess
sharing, and limit caps — asserted via dump_qos_stats.  The same
scenario runs green with osd_op_queue back to the seed FIFO (QoS fully
off = seed behavior).

The data plane is made deterministic by a fixed per-op service delay
wrapped around the shard handler (capacity = 1/delay with one shard
worker), so the fairness numbers depend on the scheduler, not on the
host's op execution speed."""

from __future__ import annotations

import hashlib
import http.client
import threading
import time

import pytest

from ceph_tpu.messages.osd_msgs import (
    OP_READ, OP_WRITEFULL, OSDOpField)
from ceph_tpu.tools.vstart import MiniCluster

pytestmark = pytest.mark.filterwarnings("ignore")

SERVICE_DELAY = 0.002       # 2 ms/op, 1 shard, 1 worker -> ~500 ops/s


def _install_service_delay(osd, delay: float = SERVICE_DELAY) -> None:
    """Fixed service time per op: the shard worker sleeps before the
    real handler, making the OSD's capacity a known constant."""
    orig = osd.opwq._handler

    def slow(klass, item, served=None):
        time.sleep(delay)
        orig(klass, item, served)
    osd.opwq._handler = slow


def _set_profiles(client, profiles: dict[str, dict]) -> int:
    epoch = 0
    for tenant, p in profiles.items():
        rc, out = client.mon_command(
            {"prefix": "qos set", "tenant": tenant, **p})
        assert rc == 0, out
    import json
    rc, out = client.mon_command({"prefix": "qos ls"})
    assert rc == 0 and set(json.loads(out)) >= set(profiles)
    return epoch


def _wait_profiles_applied(cluster, tenants, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(set(o._qos_profiles_applied) >= set(tenants)
               for o in cluster.osds.values()):
            return
        time.sleep(0.05)
    raise TimeoutError("qos_db never reached every osd")


def _served_total(dump: dict, lane: str) -> int:
    row = dump["classes"].get(lane)
    return sum(row["served"].values()) if row else 0


def _served_phase(dump: dict, lane: str, phase: str) -> int:
    row = dump["classes"].get(lane)
    return row["served"].get(phase, 0) if row else 0


class _Pump:
    """Closed-loop tenant load: n threads of synchronous small ops."""

    def __init__(self, client, pool: int, tenant: str, n_threads: int,
                 payload: bytes = b"x" * 64):
        self.client = client
        self.pool = pool
        self.tenant = tenant
        self.stop = threading.Event()
        self.counts = [0] * n_threads
        self.lat: list[float] = []
        self._lat_lock = threading.Lock()
        self.threads = [
            threading.Thread(target=self._run, args=(i, payload),
                             daemon=True, name=f"pump-{tenant}-{i}")
            for i in range(n_threads)]

    def _run(self, idx: int, payload: bytes) -> None:
        i = 0
        while not self.stop.is_set():
            oid = f"{self.tenant}-{idx}-{i % 4}"
            t0 = time.perf_counter()
            try:
                self.client.operate(
                    self.pool, oid,
                    [OSDOpField(OP_WRITEFULL, 0, len(payload), payload)],
                    tenant=self.tenant)
            except (OSError, TimeoutError):
                continue
            with self._lat_lock:
                self.lat.append(time.perf_counter() - t0)
            self.counts[idx] += 1
            i += 1

    def start(self):
        for t in self.threads:
            t.start()
        return self

    def halt(self):
        self.stop.set()

    def join(self):
        for t in self.threads:
            t.join(timeout=15)

    @property
    def total(self) -> int:
        return sum(self.counts)


PROFILES = {
    "hog": {"weight": 8.0},
    "gold": {"reservation": 100.0, "weight": 0.01},
    "silver": {"weight": 2.0},
    "bronze": {"weight": 8.0, "limit": 50.0},
}

PUMP_THREADS = {"hog": 8, "gold": 3, "silver": 4, "bronze": 4}


def _run_scenario(cluster, client, pool, warmup=1.5, measure=4.0):
    pumps = {t: _Pump(client, pool, t, n).start()
             for t, n in PUMP_THREADS.items()}
    osd = cluster.osds[0]
    try:
        time.sleep(warmup)
        d0 = osd.ctx.admin.execute("dump_qos_stats")
        t0 = time.perf_counter()
        time.sleep(measure)
        d1 = osd.ctx.admin.execute("dump_qos_stats")
        elapsed = time.perf_counter() - t0
    finally:
        for p in pumps.values():
            p.halt()
        for p in pumps.values():
            p.join()
    rates = {t: (_served_total(d1, f"client.{t}")
                 - _served_total(d0, f"client.{t}")) / elapsed
             for t in PROFILES}
    return rates, d0, d1, pumps


def test_multi_tenant_fairness_under_overload():
    """The acceptance scenario: a hog floods, gold holds >= 90% of its
    reservation, excess splits hog:silver within 20% of the 8:2 weight
    ratio, bronze never exceeds its cap by > 10% — all read from
    dump_qos_stats."""
    cluster = MiniCluster(
        n_osds=1, ms_type="loopback",
        osd_conf={"osd_op_num_shards": 1}).start()
    try:
        cluster.wait_for_osd_count(1)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8, size=1)
        _set_profiles(client, PROFILES)
        _wait_profiles_applied(cluster, PROFILES)
        _install_service_delay(cluster.osds[0])
        rates, d0, d1, _pumps = _run_scenario(cluster, client, pool)

        # reservation floor: gold >= 90% of its 100 ops/s reservation,
        # served overwhelmingly in reservation phase
        assert rates["gold"] >= 90.0, rates
        gold_res = (_served_phase(d1, "client.gold", "reservation")
                    - _served_phase(d0, "client.gold", "reservation"))
        gold_all = (_served_total(d1, "client.gold")
                    - _served_total(d0, "client.gold"))
        assert gold_res > 0.6 * gold_all, (gold_res, gold_all)

        # limit cap: bronze <= 110% of its 50 ops/s cap
        assert rates["bronze"] <= 50.0 * 1.1, rates

        # weight-proportional excess: hog:silver configured 8:2 = 4.0,
        # measured within 20%
        ratio = rates["hog"] / max(rates["silver"], 1e-9)
        assert 0.8 * 4.0 <= ratio <= 1.2 * 4.0, (ratio, rates)

        # the scheduler actually arbitrated: hog got the excess bulk
        assert rates["hog"] > rates["silver"] > 0
        # applied profiles are visible in the dump
        assert d1["profiles"]["gold"]["reservation"] == 100.0
        assert d1["queue"] == "mclock"
    finally:
        cluster.stop()


def test_same_scenario_green_on_seed_fifo():
    """QoS fully off (osd_op_queue=direct, the seed FIFO): the same
    tenants run green — no scheduler, no lanes, everyone progresses."""
    cluster = MiniCluster(
        n_osds=1, ms_type="loopback",
        osd_conf={"osd_op_queue": "direct"}).start()
    try:
        cluster.wait_for_osd_count(1)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8, size=1)
        _set_profiles(client, PROFILES)
        assert cluster.osds[0].opwq is None
        pumps = {t: _Pump(client, pool, t, 2).start()
                 for t in PROFILES}
        time.sleep(1.5)
        for p in pumps.values():
            p.halt()
        for p in pumps.values():
            p.join()
        assert all(p.total > 0 for p in pumps.values()), {
            t: p.total for t, p in pumps.items()}
        d = cluster.osds[0].ctx.admin.execute("dump_qos_stats")
        assert d["queue"] == "direct" and d["classes"] == {}
    finally:
        cluster.stop()


def test_ec_pool_tenant_lanes_and_floor():
    """Tenant lanes over an ERASURE pool across 3 OSDs: client writes
    fan out EC sub-ops while the client ops themselves ride per-tenant
    dmclock lanes on each primary; the reserved tenant draws
    reservation-phase service and nobody starves under the hog."""
    cluster = MiniCluster(
        n_osds=3, ms_type="loopback",
        osd_conf={"osd_op_num_shards": 1}).start()
    try:
        cluster.wait_for_osd_count(3)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8,
                                   pool_type="erasure", k=2, m=1)
        _set_profiles(client, {
            "hog": {"weight": 8.0},
            "gold": {"reservation": 50.0, "weight": 0.01}})
        _wait_profiles_applied(cluster, ("hog", "gold"))
        for osd in cluster.osds.values():
            _install_service_delay(osd, 0.0015)
        payload = b"e" * 2048
        pumps = {
            "hog": _Pump(client, pool, "hog", 6, payload).start(),
            "gold": _Pump(client, pool, "gold", 3, payload).start(),
        }
        time.sleep(3.0)
        for p in pumps.values():
            p.halt()
        for p in pumps.values():
            p.join()
        assert all(p.total > 3 for p in pumps.values()), {
            t: p.total for t, p in pumps.items()}
        lanes = set()
        gold_res = 0
        for osd in cluster.osds.values():
            d = osd.ctx.admin.execute("dump_qos_stats")
            lanes.update(n for n in d["classes"]
                         if n.startswith("client."))
            gold_res += _served_phase(d, "client.gold", "reservation")
        assert {"client.hog", "client.gold"} <= lanes, lanes
        assert gold_res > 0
    finally:
        cluster.stop()


# -- S3 tenant lanes under heavy traffic (multipart hog) ---------------------

class _S3Client:
    """Minimal SigV4-signing HTTP client."""

    def __init__(self, addr: str, access: str, secret: str):
        from ceph_tpu.rgw_rest import sign_request
        self._sign = sign_request
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.access = access
        self.secret = secret

    def request(self, method: str, path: str, query: str = "",
                body: bytes = b""):
        payload_sha = hashlib.sha256(body).hexdigest()
        amzdate = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {"Host": f"{self.host}:{self.port}",
                   "x-amz-date": amzdate,
                   "x-amz-content-sha256": payload_sha}
        headers["Authorization"] = self._sign(
            method, path, query,
            {"host": headers["Host"], "x-amz-date": amzdate,
             "x-amz-content-sha256": payload_sha},
            payload_sha, self.access, self.secret)
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=30)
        conn.request(method, path + (f"?{query}" if query else ""),
                     body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data, dict(resp.getheaders())


class _S3Pump:
    def __init__(self, s3: _S3Client, n_threads: int, work):
        self.s3 = s3
        self.stop = threading.Event()
        self.counts = [0] * n_threads
        self.lat: list[float] = []
        self._lock = threading.Lock()
        self.threads = [
            threading.Thread(target=self._run, args=(i, work),
                             daemon=True) for i in range(n_threads)]

    def _run(self, idx, work):
        i = 0
        while not self.stop.is_set():
            t0 = time.perf_counter()
            try:
                work(self.s3, idx, i)
            except Exception:
                continue
            with self._lock:
                self.lat.append(time.perf_counter() - t0)
            self.counts[idx] += 1
            i += 1

    def start(self):
        for t in self.threads:
            t.start()
        return self

    def halt(self):
        self.stop.set()

    def join(self):
        for t in self.threads:
            t.join(timeout=20)

    def p99(self) -> float:
        with self._lock:
            lat = sorted(self.lat)
        return lat[int(0.99 * (len(lat) - 1))] if lat else float("inf")

    @property
    def total(self):
        return sum(self.counts)


def test_s3_tenant_lanes_under_heavy_traffic():
    """Concurrent S3 clients across three tenants, 3 OSDs: the
    multipart hog saturates, the reserved tenant keeps its floor (lane
    visible in dump_qos_stats with reservation-phase service across
    the OSDs) and its p99 stays far below the hog's.  The gateway pool
    is replicated — EC pools reject omap (the bucket index), exactly
    like the reference, which keeps RGW metadata on replicated pools;
    EC-pool tenant lanes are covered by
    test_ec_pool_tenant_lanes_and_floor."""
    from ceph_tpu.rgw_rest import RgwRestServer
    auth = b"qos-s3-secret"
    cluster = MiniCluster(
        n_osds=3, ms_type="loopback", auth_key=auth,
        osd_conf={"osd_op_num_shards": 1}).start()
    srv = None
    try:
        cluster.wait_for_osd_count(3)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8, size=2)
        _set_profiles(client, {
            "hog": {"weight": 8.0},
            "gold": {"reservation": 60.0, "weight": 0.01},
            "silver": {"weight": 2.0}})
        _wait_profiles_applied(cluster, ("hog", "gold", "silver"))
        for osd in cluster.osds.values():
            _install_service_delay(osd, 0.004)
        io = client.open_ioctx(pool)
        srv = RgwRestServer(io, ctx=client.ctx,
                            frontend_workers=24).start()
        creds = {}
        for tenant in ("hog", "gold", "silver"):
            access, secret = f"AK{tenant.upper()}X", f"sk-{tenant}"
            srv.add_key(access, secret, tenant=tenant)
            creds[tenant] = _S3Client(srv.addr, access, secret)
        assert creds["hog"].request("PUT", "/uploads")[0] == 200
        assert creds["gold"].request("PUT", "/gold")[0] == 200
        assert creds["silver"].request("PUT", "/silver")[0] == 200
        part = b"p" * (48 << 10)
        st, body, _ = creds["hog"].request("POST", "/uploads/big.bin",
                                           query="uploads")
        assert st == 200
        import re
        upload_id = re.search(rb"<UploadId>([^<]+)</UploadId>",
                              body).group(1).decode()

        def hog_work(s3, idx, i):
            st, _, _ = s3.request(
                "PUT", "/uploads/big.bin",
                query=f"partNumber={(idx * 1000 + i) % 9000 + 1}"
                      f"&uploadId={upload_id}", body=part)
            assert st == 200

        small = b"s" * 512

        def gold_work(s3, idx, i):
            if i % 2:
                st, _, _ = s3.request("GET", f"/gold/o{idx}")
                assert st in (200, 404)
            else:
                st, _, _ = s3.request("PUT", f"/gold/o{idx}", body=small)
                assert st == 200

        def silver_work(s3, idx, i):
            st, _, _ = s3.request("PUT", f"/silver/o{idx}-{i % 4}",
                                  body=small)
            assert st == 200

        pumps = {
            "hog": _S3Pump(creds["hog"], 10, hog_work).start(),
            "gold": _S3Pump(creds["gold"], 3, gold_work).start(),
            "silver": _S3Pump(creds["silver"], 3, silver_work).start(),
        }
        try:
            time.sleep(5.0)
        finally:
            for p in pumps.values():
                p.halt()
            for p in pumps.values():
                p.join()
        # every tenant progressed under the hog's flood
        assert all(p.total > 3 for p in pumps.values()), {
            t: p.total for t, p in pumps.items()}
        # tenant lanes materialized on the OSDs, and gold drew
        # reservation-phase service (the dmClock floor at work)
        lanes = set()
        gold_res = 0
        for osd in cluster.osds.values():
            d = osd.ctx.admin.execute("dump_qos_stats")
            lanes.update(n for n in d["classes"]
                         if n.startswith("client."))
            gold_res += _served_phase(d, "client.gold", "reservation")
        assert {"client.hog", "client.gold",
                "client.silver"} <= lanes, lanes
        assert gold_res > 0
        # fairness shows up at the S3 surface: the reserved tenant's
        # latency distribution sits below the saturating hog's (the
        # per-tenant p99/mean the observability stack reports)
        gold_mean = sum(pumps["gold"].lat) / max(1, len(pumps["gold"].lat))
        hog_mean = sum(pumps["hog"].lat) / max(1, len(pumps["hog"].lat))
        stats = {t: (round(sum(p.lat) / max(1, len(p.lat)), 4),
                     round(p.p99(), 4)) for t, p in pumps.items()}
        assert gold_mean < hog_mean, stats
        assert pumps["gold"].p99() < 2.0 * pumps["hog"].p99(), stats
    finally:
        if srv is not None:
            srv.shutdown()
        cluster.stop()
