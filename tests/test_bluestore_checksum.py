"""BlueStore-lite data checksums + deferred writes (src/os/bluestore
checksum/deferred machinery analog): every block carries a crc32
verified on read; a bit-flip in the block file is detected and scrub
repairs the copy from a replica; small sub-block overwrites ride the
KV WAL and survive remount.
"""

from __future__ import annotations

import os

import pytest

from ceph_tpu.objectstore import Transaction, create_objectstore
from ceph_tpu.tools.vstart import MiniCluster


def _corrupt_block(store, cid: str, oid: str, flip_at: int = 100) -> None:
    """Flip one byte inside the object's first block on disk.

    The client ack can beat a replica's transaction apply landing in
    the on-disk meta under full-suite load, so poll briefly for the
    object to appear before corrupting it."""
    import time as _time
    deadline = _time.time() + 10.0
    meta = store._meta(cid, oid)
    while meta is None and _time.time() < deadline:
        _time.sleep(0.05)
        meta = store._meta(cid, oid)
    assert meta is not None, f"{cid}/{oid} never materialized in store"
    block = next(b for b in meta["extents"] if b >= 0)
    pos = block * 4096 + flip_at
    with open(store._block_path, "r+b") as f:
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ 0xFF]))


def test_bit_flip_detected_on_read(tmp_path):
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(
            Transaction().write("c.0", "victim", 0, b"payload" * 1000))
        assert st.read("c.0", "victim")[:7] == b"payload"
        _corrupt_block(st, "c.0", "victim")
        with pytest.raises(IOError, match="checksum mismatch"):
            st.read("c.0", "victim")
    finally:
        st.umount()


def test_wal_small_overwrites_roundtrip_and_survive_remount(tmp_path):
    path = str(tmp_path / "bs")
    st = create_objectstore("bluestore", path)
    st.mkfs_if_needed()
    st.mount()
    st.apply_transaction(Transaction().create_collection("c.0"))
    st.apply_transaction(Transaction().write("c.0", "o", 0, b"\xa5" * 16384))
    # sub-block patches take the deferred path; content must read back
    # correctly both via the overlay and after folding
    patches = [(100, b"one"), (4096 + 7, b"two-two"), (100, b"ONE"),
               (8192 + 4000, b"crosses-nothing"), (12288, b"z" * 4095)]
    expect = bytearray(b"\xa5" * 16384)
    for off, blob in patches:
        st.apply_transaction(Transaction().write("c.0", "o", off, blob))
        expect[off:off + len(blob)] = blob
    assert st.read("c.0", "o") == bytes(expect)
    st.umount()
    # the WAL entries are KV-journaled: a remount (crash model) replays
    st2 = create_objectstore("bluestore", path)
    st2.mount()
    try:
        assert st2.read("c.0", "o") == bytes(expect)
        # fold by exceeding WAL_MAX, then verify again
        for i in range(20):
            off = (i % 3) * 4096 + 50
            st2.apply_transaction(
                Transaction().write("c.0", "o", off, b"F"))
            expect[off:off + 1] = b"F"
        assert st2.read("c.0", "o") == bytes(expect)
    finally:
        st2.umount()


@pytest.fixture()
def bluestore_cluster(tmp_path):
    c = MiniCluster(n_osds=3, store_type="bluestore",
                    base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        yield c, client, pool, io
    finally:
        c.stop()


def _holder_pg(c, pool, oid):
    from ceph_tpu.client.rados import ceph_str_hash_rjenkins
    from ceph_tpu.osd.osdmap import pg_to_pgid
    p = c.mon.osdmap.pools[pool]
    pgnum = pg_to_pgid(ceph_str_hash_rjenkins(oid), p.pg_num)
    up, _, _, prim = c.mon.osdmap.pg_to_up_acting_osds(pool, pgnum)
    return (pool, pgnum), up, prim


def test_scrub_repairs_bit_flipped_replica(bluestore_cluster):
    c, client, pool, io = bluestore_cluster
    body = b"precious-data" * 500
    io.write_full("gold", body)
    pgid, up, prim = _holder_pg(c, pool, "gold")
    cid = f"{pgid[0]}.{pgid[1]}"
    victim = next(o for o in up if o != prim)
    _corrupt_block(c.osds[victim].store, cid, "gold")
    with pytest.raises(IOError):
        c.osds[victim].store.read(cid, "gold")
    report = c.osds[prim].scrub_pg(pgid)
    assert any(o == "gold" for o, _ in report["repaired"]), report
    import time
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            if c.osds[victim].store.read(cid, "gold") == body:
                break
        except IOError:
            pass
        time.sleep(0.1)
    assert c.osds[victim].store.read(cid, "gold") == body


def test_scrub_repairs_bit_flipped_primary(bluestore_cluster):
    c, client, pool, io = bluestore_cluster
    body = b"primary-copy" * 400
    io.write_full("crown", body)
    pgid, up, prim = _holder_pg(c, pool, "crown")
    cid = f"{pgid[0]}.{pgid[1]}"
    _corrupt_block(c.osds[prim].store, cid, "crown")
    report = c.osds[prim].scrub_pg(pgid)
    assert ("crown", prim) in report["repaired"], report
    import time
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            if c.osds[prim].store.read(cid, "crown") == body:
                break
        except IOError:
            pass
        time.sleep(0.1)
    assert c.osds[prim].store.read(cid, "crown") == body
    # the client path serves the repaired object
    assert io.read("crown") == body


def test_aborted_transaction_leaks_nothing(tmp_path):
    """A failing transaction's deferred writes and freed blocks must not
    leak into later commits (reproduced pre-fix: aborted WAL bytes
    became readable)."""
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(
            Transaction().write("c.0", "o", 0, b"\x11" * 8192))
        bad = (Transaction()
               .write("c.0", "o", 200, b"ABORT1")
               .write("c.0", "o", 300, b"ABORT2")
               .touch("nocoll", "x"))          # raises: no collection
        with pytest.raises(KeyError):
            st.apply_transaction(bad)
        # unrelated commit; then new legit deferred writes
        st.apply_transaction(Transaction().touch("c.0", "other"))
        st.apply_transaction(Transaction().write("c.0", "o", 500, b"ok"))
        data = st.read("c.0", "o")
        assert data[200:206] == b"\x11" * 6
        assert data[300:306] == b"\x11" * 6
        assert data[500:502] == b"ok"
    finally:
        st.umount()


def test_deferred_write_into_truncate_extended_region(tmp_path):
    """truncate-grow leaves size > extent coverage; a deferred write
    there must fold without crashing (reproduced pre-fix: IndexError)."""
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(
            Transaction().touch("c.0", "o1").truncate("c.0", "o1", 8192))
        st.apply_transaction(
            Transaction().write("c.0", "o1", 100, b"x" * 512))
        # force a fold through a non-deferrable op
        st.apply_transaction(Transaction().truncate("c.0", "o1", 8192))
        data = st.read("c.0", "o1")
        assert data[100:612] == b"x" * 512
        assert data[0:100] == bytes(100)
        assert len(data) == 8192
    finally:
        st.umount()


def test_scrub_pushes_over_corrupt_majority(bluestore_cluster):
    """A healthy primary facing TWO corrupt replicas pushes its copy —
    corrupt copies are never authoritative, even as a majority."""
    c, client, pool, io = bluestore_cluster
    body = b"only-healthy-copy" * 300
    io.write_full("sole", body)
    pgid, up, prim = _holder_pg(c, pool, "sole")
    cid = f"{pgid[0]}.{pgid[1]}"
    replicas = [o for o in up if o != prim]
    for r in replicas:
        _corrupt_block(c.osds[r].store, cid, "sole")
    report = c.osds[prim].scrub_pg(pgid)
    repaired_to = {o for oid, o in report["repaired"] if oid == "sole"}
    assert set(replicas) <= repaired_to, report
    import time
    for r in replicas:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if c.osds[r].store.read(cid, "sole") == body:
                    break
            except IOError:
                pass
            time.sleep(0.1)
        assert c.osds[r].store.read(cid, "sole") == body


def test_clone_overwrite_purges_destination_wal(tmp_path):
    """Cloning over an object with committed deferred writes must purge
    them — stale WAL bytes overlaying the clone was live corruption."""
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("c.0"))
        st.apply_transaction(Transaction().write("c.0", "dst", 0,
                                                 b"\x11" * 8192))
        st.apply_transaction(Transaction().write("c.0", "dst", 200,
                                                 b"OLDWAL"))
        st.apply_transaction(Transaction().write("c.0", "src", 0,
                                                 b"\x22" * 8192))
        st.apply_transaction(Transaction().clone("c.0", "src", "dst"))
        assert st.read("c.0", "dst") == b"\x22" * 8192
        # and a remove+recreate in ONE batch keeps its new deferred write
        st.apply_transaction(
            Transaction().remove("c.0", "dst")
            .write("c.0", "dst", 0, b"\x33" * 8192)
            .write("c.0", "dst", 100, b"FRESH!"))
        data = st.read("c.0", "dst")
        assert data[100:106] == b"FRESH!"
        assert data[0:100] == b"\x33" * 100
    finally:
        st.umount()


def test_coll_move_overwrite_purges_destination_wal(tmp_path):
    """collection_move over an object with committed deferred writes
    purges them (same contract as clone; reproduced corrupting reads)."""
    st = create_objectstore("bluestore", str(tmp_path / "bs"))
    st.mkfs_if_needed()
    st.mount()
    try:
        st.apply_transaction(Transaction().create_collection("a")
                             .create_collection("b"))
        st.apply_transaction(Transaction().write("b", "o", 0,
                                                 b"\x11" * 8192))
        st.apply_transaction(Transaction().write("b", "o", 200,
                                                 b"OLDWAL"))
        st.apply_transaction(Transaction().write("a", "o", 0,
                                                 b"\x22" * 8192))
        st.apply_transaction(Transaction().collection_move("a", "o", "b"))
        st.apply_transaction(Transaction().write("b", "o", 100, b"new"))
        data = st.read("b", "o")
        assert data[200:206] == b"\x22" * 6
        assert data[100:103] == b"new"
        # purge must also cover the same-batch remove+recreate+fold path
        st.apply_transaction(Transaction().write("b", "p", 0,
                                                 b"\x44" * 8192))
        st.apply_transaction(Transaction().write("b", "p", 200,
                                                 b"GHOSTS"))
        st.apply_transaction(
            Transaction().remove("b", "p")
            .write("b", "p", 0, b"\x55" * 8192)
            .write("b", "p", 100, b"ok")
            .write("b", "p", 4096, b"\x66" * 4096))
        data = st.read("b", "p")
        assert data[200:206] == b"\x55" * 6
        assert data[100:102] == b"ok"
    finally:
        st.umount()
